"""Sparse stack tests — compare against scipy.sparse / dense numpy
references (the reference's compute-vs-reference pattern; reference tests:
cpp/test/sparse/*.cu).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import sparse
from raft_tpu.distance.types import DistanceType

RNG = np.random.default_rng(0)


def random_sparse(m, n, density=0.2, seed=0):
    rng = np.random.default_rng(seed)
    dense = rng.normal(size=(m, n)).astype(np.float32)
    dense[rng.random((m, n)) > density] = 0.0
    return dense


class TestFormats:
    def test_dense_coo_roundtrip(self):
        d = random_sparse(10, 8)
        coo = sparse.dense_to_coo(jnp.asarray(d))
        back = np.asarray(sparse.coo_to_dense(coo))
        np.testing.assert_allclose(back, d, rtol=1e-6)

    def test_dense_csr_roundtrip(self):
        d = random_sparse(12, 6, seed=1)
        csr = sparse.dense_to_csr(jnp.asarray(d))
        back = np.asarray(sparse.csr_to_dense(csr))
        np.testing.assert_allclose(back, d, rtol=1e-6)

    def test_csr_indptr_matches_scipy(self):
        d = random_sparse(9, 7, seed=2)
        csr = sparse.dense_to_csr(jnp.asarray(d))
        try:
            import scipy.sparse as sp
            ref = sp.csr_matrix(d)
            np.testing.assert_array_equal(np.asarray(csr.indptr),
                                          ref.indptr)
        except ImportError:
            counts = (d != 0).sum(1)
            np.testing.assert_array_equal(
                np.asarray(jnp.diff(csr.indptr)), counts)

    def test_coo_csr_coo(self):
        d = random_sparse(6, 5, seed=3)
        coo = sparse.dense_to_coo(jnp.asarray(d))
        csr = sparse.coo_to_csr(coo)
        coo2 = sparse.csr_to_coo(csr)
        np.testing.assert_allclose(np.asarray(sparse.coo_to_dense(coo2)), d,
                                   rtol=1e-6)

    def test_capped_nnz_keeps_largest(self):
        d = np.zeros((4, 4), np.float32)
        d[0, 0], d[1, 1], d[2, 2] = 5.0, -3.0, 1.0
        coo = sparse.dense_to_coo(jnp.asarray(d), nnz=2)
        back = np.asarray(sparse.coo_to_dense(coo))
        assert back[0, 0] == 5.0 and back[1, 1] == -3.0 and back[2, 2] == 0


class TestOps:
    """sparse/op/ parity: filter, slice, row_op, duplicate reduce."""

    def test_coo_remove_scalar_and_zeros(self):
        d = random_sparse(10, 8, seed=3)
        d[d != 0] = np.round(d[d != 0] * 2)  # make some entries equal 2.0
        coo = sparse.dense_to_coo(jnp.asarray(d))
        out = sparse.coo_remove_scalar(coo, 2.0)
        expect = d.copy()
        expect[expect == 2.0] = 0.0
        np.testing.assert_allclose(np.asarray(sparse.coo_to_dense(out)),
                                   expect, rtol=1e-6)
        # removed entries become padding (sorted to the end)
        rows = np.asarray(out.rows)
        live = rows < 10
        assert not np.any(np.diff(live.astype(int)) > 0)  # no live after pad
        z = sparse.coo_remove_zeros(out)
        np.testing.assert_allclose(np.asarray(sparse.coo_to_dense(z)),
                                   expect, rtol=1e-6)

    def test_csr_row_slice(self):
        d = random_sparse(12, 6, seed=4)
        csr = sparse.dense_to_csr(jnp.asarray(d))
        sl = sparse.csr_row_slice(csr, 3, 9)
        assert sl.shape == (6, 6)
        np.testing.assert_allclose(np.asarray(sparse.csr_to_dense(sl)),
                                   d[3:9], rtol=1e-6)
        # indptr is rebased to the slice
        assert int(sl.indptr[0]) == 0
        assert int(sl.indptr[-1]) == int(np.count_nonzero(d[3:9]))

    def test_csr_row_op(self):
        d = random_sparse(8, 5, seed=5)
        csr = sparse.dense_to_csr(jnp.asarray(d))
        # scale each row's values by (row index + 1)
        out = sparse.csr_row_op(
            csr, lambda rows, idx, data: data * (rows + 1.0))
        expect = d * (np.arange(8)[:, None] + 1.0)
        np.testing.assert_allclose(np.asarray(sparse.csr_to_dense(out)),
                                   expect, rtol=1e-6)

    def test_max_duplicates(self):
        rows = jnp.asarray([0, 0, 0, 1, 1, 2], jnp.int32)
        cols = jnp.asarray([1, 1, 2, 0, 0, 2], jnp.int32)
        vals = jnp.asarray([3.0, 5.0, 1.0, -2.0, -7.0, 4.0], jnp.float32)
        coo = sparse.CooMatrix(rows, cols, vals, (3, 3))
        out = sparse.max_duplicates(coo)
        dense = np.asarray(sparse.coo_to_dense(out))
        expect = np.zeros((3, 3), np.float32)
        expect[0, 1] = 5.0   # max(3, 5)
        expect[0, 2] = 1.0
        expect[1, 0] = -2.0  # max(-2, -7)
        expect[2, 2] = 4.0
        np.testing.assert_allclose(dense, expect)
        mask = np.asarray(sparse.compute_duplicates_mask(
            sparse.coo_sort(coo)))
        assert mask.sum() == 4

    def test_sparse_distance_blocks_match_small(self):
        """Tiled two-sided densification must equal the naive dense result
        (regression for the full-y densification)."""
        from raft_tpu.distance import pairwise_distance
        dx = random_sparse(7, 9, seed=6)
        dy = random_sparse(11, 9, seed=7)
        out = sparse.pairwise_distance_sparse(
            sparse.dense_to_csr(jnp.asarray(dx)),
            sparse.dense_to_csr(jnp.asarray(dy)))
        expect = np.asarray(pairwise_distance(dx, dy))
        np.testing.assert_allclose(np.asarray(out), expect,
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("metric", [
        DistanceType.L2Expanded, DistanceType.L2SqrtExpanded,
        DistanceType.InnerProduct, DistanceType.CosineExpanded,
        DistanceType.CorrelationExpanded, DistanceType.L1,
        DistanceType.Linf])
    def test_sparse_metrics_match_dense(self, metric):
        from raft_tpu.distance import pairwise_distance
        dx = random_sparse(33, 40, seed=8)
        dy = random_sparse(17, 40, seed=9)
        out = sparse.pairwise_distance_sparse(
            sparse.dense_to_csr(jnp.asarray(dx)),
            sparse.dense_to_csr(jnp.asarray(dy)), metric)
        expect = np.asarray(pairwise_distance(dx, dy, metric))
        np.testing.assert_allclose(np.asarray(out), expect,
                                   rtol=1e-4, atol=1e-4)

    def test_sparse_expanded_column_blocking(self):
        """The column-blocked accumulation (db < dim) must equal the
        single-block result — the wide-feature regime the module
        docstring targets."""
        from raft_tpu.sparse.distance import _expanded_impl, _row_stats
        from raft_tpu.distance import pairwise_distance
        dx = random_sparse(12, 700, seed=10)
        dy = random_sparse(9, 700, seed=11)
        cx = sparse.dense_to_csr(jnp.asarray(dx))
        cy = sparse.dense_to_csr(jnp.asarray(dy))
        out = _expanded_impl(
            cx.row_ids(), cx.indices, cx.data, cy.row_ids(), cy.indices,
            cy.data, _row_stats(cx), _row_stats(cy), 12, 9, 700,
            DistanceType.L2Expanded, tile=16, db=128)
        expect = np.asarray(pairwise_distance(dx, dy,
                                              DistanceType.L2Expanded))
        np.testing.assert_allclose(np.asarray(out), expect,
                                   rtol=1e-4, atol=1e-4)


class TestLinalg:
    def test_spmv(self):
        d = random_sparse(20, 15, seed=4)
        csr = sparse.dense_to_csr(jnp.asarray(d))
        x = RNG.normal(size=15).astype(np.float32)
        np.testing.assert_allclose(np.asarray(sparse.spmv(csr, x)), d @ x,
                                   rtol=1e-4, atol=1e-5)

    def test_spmm(self):
        d = random_sparse(10, 12, seed=5)
        csr = sparse.dense_to_csr(jnp.asarray(d))
        B = RNG.normal(size=(12, 7)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(sparse.spmm(csr, B)), d @ B,
                                   rtol=1e-4, atol=1e-5)

    def test_transpose(self):
        d = random_sparse(8, 5, seed=6)
        coo = sparse.dense_to_coo(jnp.asarray(d))
        t = sparse.transpose(coo)
        np.testing.assert_allclose(np.asarray(sparse.coo_to_dense(t)), d.T,
                                   rtol=1e-6)

    def test_add_with_overlap(self):
        a = random_sparse(6, 6, seed=7)
        b = random_sparse(6, 6, seed=8)
        ca = sparse.dense_to_coo(jnp.asarray(a))
        cb = sparse.dense_to_coo(jnp.asarray(b))
        s = sparse.add(ca, cb)
        np.testing.assert_allclose(np.asarray(sparse.coo_to_dense(s)), a + b,
                                   rtol=1e-5, atol=1e-6)

    def test_symmetrize_max(self):
        # positive weights (the kNN-graph use case: structural zeros are
        # "absent", so max compares stored entries with 0)
        d = np.abs(np.triu(random_sparse(6, 6, seed=9)))
        coo = sparse.dense_to_coo(jnp.asarray(d))
        s = sparse.symmetrize(coo, op="max")
        out = np.asarray(sparse.coo_to_dense(s))
        ref = np.maximum(d, d.T)
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_degree(self):
        d = random_sparse(7, 7, seed=10)
        coo = sparse.dense_to_coo(jnp.asarray(d))
        np.testing.assert_array_equal(np.asarray(sparse.degree(coo)),
                                      (d != 0).sum(1))

    def test_row_norm(self):
        d = random_sparse(9, 4, seed=11)
        csr = sparse.dense_to_csr(jnp.asarray(d))
        np.testing.assert_allclose(
            np.asarray(sparse.row_norm_csr(csr, "l2")),
            np.linalg.norm(d, axis=1), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            np.asarray(sparse.row_norm_csr(csr, "l1")),
            np.abs(d).sum(1), rtol=1e-4, atol=1e-5)

    def test_laplacian_spmv(self):
        # small symmetric adjacency
        d = random_sparse(8, 8, seed=12)
        adj = np.abs(np.minimum(d, d.T))
        np.fill_diagonal(adj, 0)
        coo = sparse.dense_to_coo(jnp.asarray(adj))
        lap_csr, diag = sparse.laplacian(coo, normalized=False)
        x = RNG.normal(size=8).astype(np.float32)
        L = np.diag(adj.sum(1)) - adj
        np.testing.assert_allclose(
            np.asarray(sparse.laplacian_spmv(lap_csr, diag, x)), L @ x,
            rtol=1e-4, atol=1e-4)


class TestDistanceNeighbors:
    def test_sparse_pairwise_matches_dense(self):
        a = random_sparse(15, 10, seed=13)
        b = random_sparse(12, 10, seed=14)
        ca = sparse.dense_to_csr(jnp.asarray(a))
        cb = sparse.dense_to_csr(jnp.asarray(b))
        out = np.asarray(sparse.pairwise_distance_sparse(ca, cb, 0))
        ref = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-3)

    def test_sparse_knn(self):
        a = random_sparse(10, 8, seed=15)
        b = random_sparse(30, 8, seed=16)
        ca = sparse.dense_to_csr(jnp.asarray(a))
        cb = sparse.dense_to_csr(jnp.asarray(b))
        d, i = sparse.brute_force_knn_sparse(ca, cb, 5)
        ref = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        # tie-aware exactness (zero rows in a/b produce multi-way distance
        # ties, so index sets are ambiguous — the reference's ANN tests use
        # distance-tolerant eval too, ann_utils.cuh:125): every selected
        # neighbor must be within the true k-th distance, and the returned
        # distances must equal the true sorted top-k.
        kth = np.sort(ref, axis=1)[:, 4]
        picked = np.take_along_axis(ref, np.asarray(i), axis=1)
        assert (picked <= kth[:, None] + 1e-4).all()
        np.testing.assert_allclose(np.sort(np.asarray(d), axis=1),
                                   np.sort(ref, axis=1)[:, :5],
                                   rtol=1e-3, atol=1e-3)

    def test_knn_graph_symmetric(self, res):
        X = RNG.normal(size=(50, 4)).astype(np.float32)
        g = sparse.knn_graph(res, X, 4)
        dense = np.asarray(sparse.coo_to_dense(g))
        np.testing.assert_allclose(dense, dense.T, rtol=1e-5, atol=1e-6)
        # each row has >= k nonzeros (k out-edges plus mirrored in-edges)
        assert ((dense > 0).sum(1) >= 4).all()

    def test_connect_components(self, res):
        # two well-separated blobs with distinct labels
        X = np.concatenate([RNG.normal(size=(10, 2)),
                            RNG.normal(size=(10, 2)) + 20]).astype(np.float32)
        labels = np.asarray([0] * 10 + [1] * 10, np.int32)
        src, dst, dist = sparse.connect_components(res, X, labels)
        src, dst = np.asarray(src), np.asarray(dst)
        valid = src >= 0
        assert valid.sum() == 2  # one candidate per component
        for s, t in zip(src[valid], dst[valid]):
            assert labels[s] != labels[t]


class TestSolvers:
    def test_lanczos_smallest_vs_numpy(self, res):
        # symmetric PSD matrix
        A = random_sparse(30, 30, seed=17)
        A = A @ A.T + np.eye(30, dtype=np.float32)
        csr = sparse.dense_to_csr(jnp.asarray(A))
        vals, vecs = sparse.eigsh_smallest(res, csr, 3, ncv=25)
        ref = np.linalg.eigvalsh(A)[:3]
        np.testing.assert_allclose(np.sort(np.asarray(vals)), ref,
                                   rtol=1e-2, atol=1e-2)
        # residuals ||A v - λ v|| small
        for j in range(3):
            v = np.asarray(vecs[:, j])
            lam = float(vals[j])
            assert np.linalg.norm(A @ v - lam * v) < 0.1 * max(1, abs(lam))

    def test_lanczos_largest(self, res):
        A = random_sparse(25, 25, seed=18)
        A = (A + A.T) / 2
        csr = sparse.dense_to_csr(jnp.asarray(A))
        vals, _ = sparse.eigsh_largest(res, csr, 2, ncv=22)
        ref = np.linalg.eigvalsh(A)[::-1][:2]
        np.testing.assert_allclose(np.asarray(vals), ref, rtol=1e-2,
                                   atol=1e-2)

    def test_mst_path_graph(self, res):
        # path graph 0-1-2-3 with increasing weights + one heavy extra edge
        rows = np.asarray([0, 1, 2, 0, 1, 2, 3, 3], np.int32)
        cols = np.asarray([1, 2, 3, 3, 0, 1, 2, 0], np.int32)
        w = np.asarray([1, 2, 3, 10, 1, 2, 3, 10], np.float32)
        coo = sparse.CooMatrix(jnp.asarray(rows), jnp.asarray(cols),
                               jnp.asarray(w), (4, 4))
        src, dst, weight, color = sparse.mst(res, coo)
        weight = np.asarray(weight)
        total = weight[np.isfinite(weight)].sum()
        assert total == 6.0  # 1 + 2 + 3
        # all vertices in one component
        assert len(np.unique(np.asarray(color))) == 1

    def test_mst_random_graph_vs_scipy(self, res):
        try:
            from scipy.sparse.csgraph import minimum_spanning_tree
            import scipy.sparse as sp
        except ImportError:
            pytest.skip("scipy needed")
        n = 20
        d = RNG.random((n, n)).astype(np.float32)
        d = np.triu(d, 1)
        full = d + d.T
        ref = minimum_spanning_tree(sp.csr_matrix(full)).sum()
        rows, cols = np.nonzero(full)
        coo = sparse.CooMatrix(jnp.asarray(rows.astype(np.int32)),
                               jnp.asarray(cols.astype(np.int32)),
                               jnp.asarray(full[rows, cols]), (n, n))
        src, dst, weight, color = sparse.mst(res, coo)
        weight = np.asarray(weight)
        total = weight[np.isfinite(weight)].sum()
        np.testing.assert_allclose(total, ref, rtol=1e-4)
        assert len(np.unique(np.asarray(color))) == 1
