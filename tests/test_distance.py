"""Distance tests — all 20 metrics vs scipy (reference analogue:
cpp/test/distance/ naive-kernel comparisons; pylibraft test_distance.py uses
scipy.cdist the same way)."""

import jax.numpy as jnp
import numpy as np
import pytest
from scipy.spatial import distance as sp_dist

from raft_tpu.distance import (
    DistanceType,
    fused_l2_nn,
    gram_matrix,
    KernelParams,
    KernelType,
    masked_l2_nn,
    pairwise_distance,
)

RNG = np.random.default_rng(99)


def make_xy(m=33, n=47, k=17, positive=False):
    x = RNG.normal(size=(m, k)).astype(np.float32)
    y = RNG.normal(size=(n, k)).astype(np.float32)
    if positive:
        x, y = np.abs(x) + 0.01, np.abs(y) + 0.01
    return x, y


SCIPY_METRICS = [
    # expanded forms trade precision for MXU throughput (fp32 cancellation);
    # the reference's expanded path has the same property
    (DistanceType.L2SqrtExpanded, "euclidean", False, 5e-3),
    (DistanceType.L2Expanded, "sqeuclidean", False, 5e-3),
    (DistanceType.L2SqrtUnexpanded, "euclidean", False, 1e-4),
    (DistanceType.L2Unexpanded, "sqeuclidean", False, 1e-4),
    (DistanceType.CosineExpanded, "cosine", False, 5e-3),
    (DistanceType.CorrelationExpanded, "correlation", False, 5e-3),
    (DistanceType.L1, "cityblock", False, 1e-4),
    (DistanceType.Linf, "chebyshev", False, 1e-5),
    (DistanceType.Canberra, "canberra", False, 1e-4),
    (DistanceType.BrayCurtis, "braycurtis", True, 1e-4),
    (DistanceType.JensenShannon, "jensenshannon", True, 1e-3),
]


class TestPairwiseDistance:
    @pytest.mark.parametrize("metric,scipy_name,positive,tol", SCIPY_METRICS,
                             ids=[m[1] + "_" + str(int(m[0])) for m in SCIPY_METRICS])
    def test_vs_scipy(self, metric, scipy_name, positive, tol):
        x, y = make_xy(positive=positive)
        if metric == DistanceType.JensenShannon:
            x /= x.sum(1, keepdims=True)
            y /= y.sum(1, keepdims=True)
        out = np.asarray(pairwise_distance(x, y, metric))
        ref = sp_dist.cdist(x, y, scipy_name)
        np.testing.assert_allclose(out, ref, rtol=tol, atol=tol)

    def test_minkowski(self):
        x, y = make_xy()
        out = np.asarray(pairwise_distance(x, y, DistanceType.LpUnexpanded,
                                           metric_arg=3.0))
        ref = sp_dist.cdist(x, y, "minkowski", p=3.0)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_inner_product(self):
        x, y = make_xy()
        np.testing.assert_allclose(
            np.asarray(pairwise_distance(x, y, DistanceType.InnerProduct)),
            x @ y.T, rtol=1e-4, atol=1e-4)

    def test_hellinger(self):
        x, y = make_xy(positive=True)
        x /= x.sum(1, keepdims=True)
        y /= y.sum(1, keepdims=True)
        out = np.asarray(pairwise_distance(x, y, DistanceType.HellingerExpanded))
        ref = np.sqrt(np.maximum(
            1 - (np.sqrt(x)[:, None, :] * np.sqrt(y)[None, :, :]).sum(-1), 0))
        np.testing.assert_allclose(out, ref, atol=1e-3)

    def test_kl_divergence(self):
        x, y = make_xy(positive=True)
        x /= x.sum(1, keepdims=True)
        y /= y.sum(1, keepdims=True)
        out = np.asarray(pairwise_distance(x, y, DistanceType.KLDivergence))
        ref = (x[:, None, :] * np.log(x[:, None, :] / y[None, :, :])).sum(-1)
        np.testing.assert_allclose(out, ref, atol=1e-3)

    def test_kl_divergence_zero_y(self):
        # y_j == 0 contributes nothing to the cross term (reference:
        # distance_ops/kl_divergence.cuh:66 zeroes log(y) at y==0)
        x = np.asarray([[0.5, 0.5, 0.0]], np.float32)
        y = np.asarray([[0.5, 0.0, 0.5]], np.float32)
        out = np.asarray(pairwise_distance(x, y, DistanceType.KLDivergence))
        # x log x = log(0.5); cross keeps only j=0 (x_1>0 but y_1==0 dropped,
        # x_2==0 dropped) = 0.5*log(0.5); result = 0.5*log(0.5)
        np.testing.assert_allclose(out[0, 0], 0.5 * np.log(0.5), atol=1e-5)

    def test_hamming(self):
        x = (RNG.random((20, 30)) > 0.5).astype(np.float32)
        y = (RNG.random((25, 30)) > 0.5).astype(np.float32)
        out = np.asarray(pairwise_distance(x, y, DistanceType.HammingUnexpanded))
        ref = sp_dist.cdist(x, y, "hamming")
        np.testing.assert_allclose(out, ref, atol=1e-5)

    @pytest.mark.parametrize("metric,name", [
        (DistanceType.JaccardExpanded, "jaccard"),
        (DistanceType.DiceExpanded, "dice"),
        (DistanceType.RusselRaoExpanded, "russellrao"),
    ])
    def test_boolean_metrics(self, metric, name):
        x = (RNG.random((20, 32)) > 0.5)
        y = (RNG.random((22, 32)) > 0.5)
        out = np.asarray(pairwise_distance(x.astype(np.float32),
                                           y.astype(np.float32), metric))
        ref = sp_dist.cdist(x, y, name)
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_haversine(self):
        lat = RNG.uniform(-np.pi / 2, np.pi / 2, size=(10, 1))
        lon = RNG.uniform(-np.pi, np.pi, size=(10, 1))
        pts = np.concatenate([lat, lon], 1).astype(np.float32)
        out = np.asarray(pairwise_distance(pts, pts, DistanceType.Haversine))
        assert np.allclose(np.diagonal(out), 0, atol=1e-4)
        assert np.allclose(out, out.T, atol=1e-4)

    def test_metric_names(self):
        x, y = make_xy(m=5, n=6, k=4)
        np.testing.assert_allclose(
            np.asarray(pairwise_distance(x, y, "euclidean")),
            sp_dist.cdist(x, y, "euclidean"), rtol=1e-3, atol=1e-3)

    def test_shape_validation(self):
        from raft_tpu.core import LogicError
        with pytest.raises(LogicError):
            pairwise_distance(np.zeros((3, 4)), np.zeros((3, 5)))


class TestFusedL2NN:
    def test_matches_bruteforce(self):
        x, y = make_xy(m=200, n=5000, k=16)
        d, i = fused_l2_nn(jnp.asarray(x), jnp.asarray(y), tile_n=512)
        full = sp_dist.cdist(x, y, "sqeuclidean")
        np.testing.assert_array_equal(np.asarray(i), full.argmin(1))
        np.testing.assert_allclose(np.asarray(d), full.min(1), rtol=1e-3,
                                   atol=1e-3)

    def test_sqrt_mode(self):
        x, y = make_xy(m=20, n=100, k=8)
        d, _ = fused_l2_nn(jnp.asarray(x), jnp.asarray(y), sqrt=True)
        full = sp_dist.cdist(x, y, "euclidean")
        np.testing.assert_allclose(np.asarray(d), full.min(1), rtol=1e-3,
                                   atol=1e-3)


class TestMaskedNN:
    def test_mask_respected(self):
        x, y = make_xy(m=10, n=30, k=4)
        # 3 groups of 10 rows each; end offsets
        group_idxs = jnp.asarray([10, 20, 30])
        adj = np.zeros((10, 3), bool)
        adj[:, 1] = True  # only middle group allowed
        d, i = masked_l2_nn(jnp.asarray(x), jnp.asarray(y),
                            jnp.asarray(adj), group_idxs)
        full = sp_dist.cdist(x, y[10:20], "sqeuclidean")
        np.testing.assert_array_equal(np.asarray(i), full.argmin(1) + 10)
        np.testing.assert_allclose(np.asarray(d), full.min(1), rtol=1e-3,
                                   atol=1e-3)


class TestGram:
    def test_rbf_poly_tanh(self):
        x, y = make_xy(m=12, n=9, k=5)
        lin = np.asarray(gram_matrix(jnp.asarray(x), jnp.asarray(y)))
        np.testing.assert_allclose(lin, x @ y.T, rtol=1e-4, atol=1e-4)
        rbf = np.asarray(gram_matrix(
            jnp.asarray(x), jnp.asarray(y),
            KernelParams(KernelType.RBF, gamma=0.3)))
        ref = np.exp(-0.3 * sp_dist.cdist(x, y, "sqeuclidean"))
        np.testing.assert_allclose(rbf, ref, rtol=1e-3, atol=1e-3)
        poly = np.asarray(gram_matrix(
            jnp.asarray(x), jnp.asarray(y),
            KernelParams(KernelType.POLYNOMIAL, degree=2, gamma=0.5, coef0=1.0)))
        np.testing.assert_allclose(poly, (0.5 * x @ y.T + 1) ** 2, rtol=1e-3,
                                   atol=1e-3)
