"""Resilience subsystem tests: deterministic fault injection, retry /
deadline wrappers, checkpointed builds with resume, hardened (CRC
enveloped) serialization, and degraded-mode distributed search.
"""

import io
import os
import threading

import numpy as np
import pytest

from raft_tpu import observability as obs
from raft_tpu.core.interruptible import InterruptedException, interruptible
from raft_tpu.core import serialize as ser
from raft_tpu.core.serialize import CorruptIndexError
from raft_tpu.resilience import (
    CheckpointManager,
    Deadline,
    DeadlineExceededError,
    FaultPlan,
    RetryPolicy,
    TransientFault,
    atomic_write,
    faults,
    inject,
    retry_call,
)
from raft_tpu.resilience import retry as retry_mod


@pytest.fixture(autouse=True)
def _no_sleep(monkeypatch):
    # run every backoff schedule instantly; delays are asserted, not slept
    monkeypatch.setattr(retry_mod, "_sleep", lambda s: None)


@pytest.fixture
def fresh_res():
    from raft_tpu import DeviceResources
    return lambda: DeviceResources(seed=42)


# ---------------------------------------------------------------------------
# fault injection
# ---------------------------------------------------------------------------

class TestFaults:
    def test_inactive_is_noop(self):
        assert not faults.is_active()
        faults.maybe_fail("comms.allreduce")  # no plan: must not raise

    def test_times_bounds_firing(self):
        plan = FaultPlan(seed=0).at("site.a", times=2)
        with plan.active():
            for _ in range(2):
                with pytest.raises(TransientFault):
                    faults.maybe_fail("site.a")
            faults.maybe_fail("site.a")  # budget spent
        assert plan.specs[0].fired == 2

    def test_after_skips_leading_calls(self):
        plan = FaultPlan(seed=0).at("site.b", times=1, after=2)
        with plan.active():
            faults.maybe_fail("site.b")
            faults.maybe_fail("site.b")
            with pytest.raises(TransientFault):
                faults.maybe_fail("site.b")

    def test_probability_is_seed_deterministic(self):
        def run(seed):
            hits = []
            plan = FaultPlan(seed=seed).at("site.c", times=None, p=0.5)
            with plan.active():
                for i in range(32):
                    try:
                        faults.maybe_fail("site.c")
                        hits.append(0)
                    except TransientFault:
                        hits.append(1)
            return hits

        a, b = run(123), run(123)
        assert a == b
        assert 0 < sum(a) < 32

    def test_seed_env_pins_default(self, monkeypatch):
        monkeypatch.setenv("RAFT_TPU_FAULT_SEED", "777")
        assert FaultPlan().seed == 777

    def test_custom_exception(self):
        with inject("site.d", exc=InterruptedException):
            with pytest.raises(InterruptedException):
                faults.maybe_fail("site.d")

    def test_nested_plans_are_lifo(self):
        outer = FaultPlan(seed=0).at("site.e")
        inner = FaultPlan(seed=0).at("site.f")
        with outer.active():
            with inner.active():
                faults.maybe_fail("site.e")  # outer shadowed
                with pytest.raises(TransientFault):
                    faults.maybe_fail("site.f")
            with pytest.raises(TransientFault):
                faults.maybe_fail("site.e")
        assert not faults.is_active()

    def test_injection_counter(self):
        obs.reset()
        with obs.collecting():
            with inject("site.g"):
                with pytest.raises(TransientFault):
                    faults.maybe_fail("site.g")
        c = obs.snapshot()["counters"]
        assert c.get("resilience.fault.injected.site.g") == 1

    def test_failed_shards_clipped(self):
        plan = FaultPlan(seed=0).fail_shards(1, 5, 99, -3)
        with plan.active():
            assert faults.failed_shards(8) == (1, 5)
        assert faults.failed_shards(8) == ()


# ---------------------------------------------------------------------------
# latency / straggler injection (PR 12)
# ---------------------------------------------------------------------------

class TestLatencyInjection:
    @pytest.fixture(autouse=True)
    def _recorded_sleep(self, monkeypatch):
        # late-bound so a test may swap self.slept for a fresh list
        self.slept = []
        monkeypatch.setattr(faults, "_sleep",
                            lambda s: self.slept.append(s))

    def test_delay_spec_sleeps_instead_of_raising(self):
        plan = FaultPlan(seed=0).delay_at("site.lat", delay=0.25)
        with plan.active():
            faults.maybe_fail("site.lat")     # must NOT raise
            faults.maybe_fail("site.lat")     # unbounded by default
        assert self.slept == [0.25, 0.25]
        assert plan.specs[0].fired == 2

    def test_times_and_after_bound_delays(self):
        plan = FaultPlan(seed=0).delay_at("site.lat", delay=0.1,
                                          times=1, after=1)
        with plan.active():
            faults.maybe_fail("site.lat")     # skipped (after=1)
            faults.maybe_fail("site.lat")     # fires
            faults.maybe_fail("site.lat")     # budget spent
        assert self.slept == [0.1]

    def test_jitter_is_seed_deterministic(self):
        def run(seed):
            slept = []
            self.slept = slept  # capture this run only
            plan = FaultPlan(seed=seed).delay_at("site.jit", delay=0.01,
                                                 jitter=0.05)
            with plan.active():
                for _ in range(8):
                    faults.maybe_fail("site.jit")
            return slept

        a, b = run(99), run(99)
        assert a == b
        assert all(0.01 <= s <= 0.06 for s in a)
        assert len(set(a)) > 1                # jitter actually varies
        assert run(100) != a                  # and the seed matters

    def test_delay_counter(self):
        obs.reset()
        with obs.collecting():
            plan = FaultPlan(seed=0).delay_at("site.cnt", delay=0.2)
            with plan.active():
                faults.maybe_fail("site.cnt")
        c = obs.snapshot()["counters"]
        assert c.get("resilience.fault.delayed.site.cnt") == 1
        assert "resilience.fault.injected.site.cnt" not in c

    def test_delay_and_failure_coexist_at_one_site(self):
        plan = (FaultPlan(seed=0)
                .delay_at("site.both", delay=0.3)
                .at("site.both", times=1))
        with plan.active():
            with pytest.raises(TransientFault):
                faults.maybe_fail("site.both")   # slept, then raised
            faults.maybe_fail("site.both")       # failure budget spent
        assert self.slept == [0.3, 0.3]

    def test_negative_delay_rejected(self):
        with pytest.raises(ValueError):
            FaultPlan(seed=0).delay_at("site.x", delay=-1.0)
        with pytest.raises(ValueError):
            FaultPlan(seed=0).straggle_shard(0, delay=0.1, jitter=-0.1)

    def test_straggler_pause_inactive_is_noop(self):
        assert faults.straggler_pause(8) == ()
        assert self.slept == []

    def test_straggler_pause_sleeps_the_max(self):
        obs.reset()
        plan = (FaultPlan(seed=0)
                .straggle_shard(1, delay=0.2)
                .straggle_shard(3, delay=0.1))
        with obs.collecting(), plan.active():
            delays = faults.straggler_pause(4)
        assert delays == (0.0, 0.2, 0.0, 0.1)
        assert self.slept == [0.2]            # ONE pause: the slowest shard
        c = obs.snapshot()["counters"]
        assert c.get("resilience.fault.delayed.distributed.straggler") == 1

    def test_straggler_jitter_deterministic(self):
        def run(seed):
            plan = FaultPlan(seed=seed).straggle_shard(2, delay=0.05,
                                                       jitter=0.02)
            with plan.active():
                return [faults.straggler_pause(4) for _ in range(4)]

        a, b = run(5), run(5)
        assert a == b
        assert all(0.05 <= d[2] <= 0.07 and d[0] == 0.0 for d in a)


# ---------------------------------------------------------------------------
# retry / deadline
# ---------------------------------------------------------------------------

class TestRetry:
    def test_recovers_after_transient(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise TransientFault("flaky")
            return "ok"

        obs.reset()
        with obs.collecting():
            out = retry_call(flaky, site="t.recover",
                             policy=RetryPolicy(max_attempts=3))
        assert out == "ok" and len(calls) == 3
        c = obs.snapshot()["counters"]
        assert c.get("resilience.retry.t.recover") == 2
        assert "resilience.giveup.t.recover" not in c

    def test_exhaustion_raises_and_counts_giveup(self):
        def always():
            raise TransientFault("always")

        obs.reset()
        with obs.collecting():
            with pytest.raises(TransientFault):
                retry_call(always, site="t.exhaust",
                           policy=RetryPolicy(max_attempts=3))
        c = obs.snapshot()["counters"]
        assert c.get("resilience.retry.t.exhaust") == 2
        assert c.get("resilience.giveup.t.exhaust") == 1

    def test_non_retryable_fails_fast(self):
        calls = []

        def logic_error():
            calls.append(1)
            raise ValueError("deterministic")

        with pytest.raises(ValueError):
            retry_call(logic_error, site="t.logic")
        assert len(calls) == 1

    def test_file_not_found_not_retried(self):
        # FileNotFoundError is OSError but deterministic: listed
        # non-retryable so it is not pointlessly re-attempted
        calls = []

        def missing():
            calls.append(1)
            raise FileNotFoundError("/nope")

        with pytest.raises(FileNotFoundError):
            retry_call(missing, site="t.missing")
        assert len(calls) == 1

    def test_deadline_expiry(self):
        t = {"now": 0.0}
        dl = Deadline(10.0, clock=lambda: t["now"])
        assert dl.remaining() == 10.0
        t["now"] = 11.0
        assert dl.expired
        with pytest.raises(DeadlineExceededError):
            dl.check("op")

    def test_deadline_stops_retries(self):
        t = {"now": 0.0}

        def always():
            t["now"] += 6.0  # each attempt burns 6 "seconds"
            raise TransientFault("slow")

        obs.reset()
        with obs.collecting():
            with pytest.raises(DeadlineExceededError):
                retry_call(always, site="t.deadline",
                           policy=RetryPolicy(max_attempts=100),
                           deadline=Deadline(10.0, clock=lambda: t["now"]))
        c = obs.snapshot()["counters"]
        assert c.get("resilience.giveup.t.deadline") == 1

    def test_unlimited_deadline(self):
        dl = Deadline.unlimited()
        assert dl.remaining() == float("inf") and not dl.expired

    def test_backoff_schedule_and_jitter_determinism(self):
        import random
        pol = RetryPolicy(base_delay=0.1, multiplier=2.0, max_delay=1.0,
                          jitter=0.0)
        assert pol.delay(1) == pytest.approx(0.1)
        assert pol.delay(2) == pytest.approx(0.2)
        assert pol.delay(10) == pytest.approx(1.0)  # capped
        jit = RetryPolicy(base_delay=0.1, jitter=0.5)
        a = [jit.delay(i, random.Random(5)) for i in range(1, 4)]
        b = [jit.delay(i, random.Random(5)) for i in range(1, 4)]
        assert a == b

    def test_retryable_decorator(self):
        from raft_tpu.resilience import retryable
        calls = []

        @retryable("t.deco")
        def flaky(x):
            calls.append(1)
            if len(calls) < 2:
                raise TransientFault("once")
            return x + 1

        assert flaky(41, retry_policy=RetryPolicy(max_attempts=2)) == 42
        assert len(calls) == 2


# ---------------------------------------------------------------------------
# serialization hardening: short reads, envelope CRC
# ---------------------------------------------------------------------------

class TestSerializationHardening:
    def test_scalar_roundtrip(self):
        buf = io.BytesIO()
        ser.serialize_scalar(None, buf, np.int32(42))
        buf.seek(0)
        assert int(ser.deserialize_scalar(None, buf)) == 42

    def test_scalar_short_read_reports_offsets(self):
        buf = io.BytesIO()
        ser.serialize_scalar(None, buf, np.int64(7))
        raw = buf.getvalue()
        with pytest.raises(CorruptIndexError, match="byte"):
            ser.deserialize_scalar(None, io.BytesIO(raw[:-3]))

    def test_scalar_bad_magic(self):
        with pytest.raises(CorruptIndexError):
            ser.deserialize_scalar(None, io.BytesIO(b"XXXX\x03<i4" + b"\0" * 4))

    def test_scalar_empty_stream(self):
        with pytest.raises(CorruptIndexError):
            ser.deserialize_scalar(None, io.BytesIO(b""))

    def test_mdspan_truncation(self):
        buf = io.BytesIO()
        ser.serialize_mdspan(None, buf, np.arange(100, dtype=np.float32))
        raw = buf.getvalue()
        with pytest.raises(CorruptIndexError):
            ser.deserialize_mdspan(None, io.BytesIO(raw[: len(raw) // 2]))

    def test_envelope_roundtrip(self):
        payload = os.urandom(300)
        buf = io.BytesIO()
        ser.write_envelope(buf, payload)
        buf.seek(0)
        assert ser.read_envelope(buf) == payload

    def test_envelope_property_random_mutations(self):
        # property test: any single-byte flip or truncation of an
        # enveloped stream must raise CorruptIndexError — never load
        rng = np.random.default_rng(1234)
        for trial in range(50):
            payload = rng.integers(0, 256,
                                   int(rng.integers(1, 512))).astype(
                                       np.uint8).tobytes()
            buf = io.BytesIO()
            ser.write_envelope(buf, payload)
            raw = bytearray(buf.getvalue())
            for _ in range(3):
                mutated = bytearray(raw)
                pos = int(rng.integers(0, len(mutated)))
                old = mutated[pos]
                mutated[pos] = old ^ int(rng.integers(1, 256))
                with pytest.raises(CorruptIndexError):
                    ser.read_envelope(io.BytesIO(bytes(mutated)))
            cut = int(rng.integers(0, len(raw)))
            with pytest.raises(CorruptIndexError):
                ser.read_envelope(io.BytesIO(bytes(raw[:cut])))

    def test_envelope_version_gate(self):
        buf = io.BytesIO()
        ser.write_envelope(buf, b"abc")
        raw = bytearray(buf.getvalue())
        raw[4] = 99  # format version (little-endian u16 low byte)
        with pytest.raises(CorruptIndexError, match="version"):
            ser.read_envelope(io.BytesIO(bytes(raw)))

    def test_serialize_write_fault_site(self):
        with inject("serialize.write"):
            with pytest.raises(TransientFault):
                ser.serialize_scalar(None, io.BytesIO(), np.int32(1))


# ---------------------------------------------------------------------------
# corruption round-trips per index type (S4)
# ---------------------------------------------------------------------------

def _build_small(kind, res):
    rng = np.random.default_rng(3)
    db = rng.standard_normal((256, 16), dtype=np.float32)
    if kind == "ivf_flat":
        from raft_tpu.neighbors import ivf_flat as m
        idx = m.build(res, m.IndexParams(n_lists=8, kmeans_n_iters=2), db)
    elif kind == "ivf_pq":
        from raft_tpu.neighbors import ivf_pq as m
        idx = m.build(res, m.IndexParams(n_lists=8, kmeans_n_iters=2,
                                         pq_dim=4), db)
    else:
        from raft_tpu.neighbors import cagra as m
        idx = m.build(res, m.IndexParams(intermediate_graph_degree=16,
                                         graph_degree=8), db)
    return m, idx


@pytest.mark.parametrize("kind", ["ivf_flat", "ivf_pq", "cagra"])
class TestIndexCorruptionRoundTrip:
    def test_corruption_always_detected(self, kind, res):
        m, idx = _build_small(kind, res)
        buf = io.BytesIO()
        m.serialize(res, buf, idx)
        raw = buf.getvalue()
        # clean load still works
        m.deserialize(res, io.BytesIO(raw))
        rng = np.random.default_rng(99)
        for _ in range(8):
            mutated = bytearray(raw)
            pos = int(rng.integers(0, len(mutated)))
            mutated[pos] ^= int(rng.integers(1, 256))
            with pytest.raises(CorruptIndexError):
                m.deserialize(res, io.BytesIO(bytes(mutated)))
        for frac in (0.0, 0.3, 0.9):
            cut = int(len(raw) * frac)
            with pytest.raises(CorruptIndexError):
                m.deserialize(res, io.BytesIO(raw[:cut]))

    def test_save_load_file_overloads(self, kind, res, tmp_path):
        m, idx = _build_small(kind, res)
        path = str(tmp_path / f"{kind}.idx")
        m.save(res, path, idx)
        m.load(res, path)
        # no torn tmp files left behind by the atomic protocol
        assert [f for f in os.listdir(tmp_path) if ".tmp." in f] == []

    def test_save_retries_transient_write_fault(self, kind, res, tmp_path):
        m, idx = _build_small(kind, res)
        path = str(tmp_path / f"{kind}_retry.idx")
        obs.reset()
        with obs.collecting():
            with inject("serialize.write", times=1):
                m.save(res, path, idx)
        c = obs.snapshot()["counters"]
        assert c.get(f"resilience.retry.{kind}.save") == 1
        m.load(res, path)  # payload landed whole despite the fault

    def test_load_missing_file_fails_fast(self, kind, res, tmp_path):
        m, _ = _build_small(kind, res)
        obs.reset()
        with obs.collecting():
            with pytest.raises(FileNotFoundError):
                m.load(res, str(tmp_path / "absent.idx"))
        c = obs.snapshot()["counters"]
        assert f"resilience.retry.{kind}.load" not in c
        assert c.get(f"resilience.giveup.{kind}.load") == 1


# ---------------------------------------------------------------------------
# checkpoints
# ---------------------------------------------------------------------------

class TestCheckpoint:
    def test_roundtrip_and_manifest_order(self, tmp_path):
        ck = CheckpointManager(str(tmp_path / "ck"))
        a = np.arange(12, dtype=np.float32).reshape(3, 4)
        b = np.array([1, -2, 3], dtype=np.int32)
        ck.save("one", {"a": a})
        ck.save("two", {"a": a, "b": b})
        assert ck.completed == ["one", "two"]
        got = ck.load("two")
        np.testing.assert_array_equal(got["a"], a)
        np.testing.assert_array_equal(got["b"], b)
        # a re-opened manager sees the same durable state
        ck2 = CheckpointManager(str(tmp_path / "ck"))
        assert ck2.has("one") and ck2.has("two")

    def test_clear(self, tmp_path):
        ck = CheckpointManager(str(tmp_path / "ck"))
        ck.save("s", {"x": np.zeros(2)})
        ck.clear()
        assert not ck.has("s") and ck.completed == []

    def test_corrupt_stage_raises(self, tmp_path):
        ck = CheckpointManager(str(tmp_path / "ck"))
        ck.save("s", {"x": np.arange(64, dtype=np.float64)})
        p = os.path.join(ck.path, "s.ckpt")
        raw = bytearray(open(p, "rb").read())
        raw[len(raw) // 2] ^= 0x40
        open(p, "wb").write(bytes(raw))
        with pytest.raises(CorruptIndexError):
            ck.load("s")

    def test_atomic_write_replaces(self, tmp_path):
        p = str(tmp_path / "f.bin")
        atomic_write(p, b"v1")
        atomic_write(p, b"v2-longer")
        assert open(p, "rb").read() == b"v2-longer"
        assert [f for f in os.listdir(tmp_path) if ".tmp." in f] == []

    def test_save_fault_site(self, tmp_path):
        ck = CheckpointManager(str(tmp_path / "ck"))
        with inject("checkpoint.save"):
            with pytest.raises(TransientFault):
                ck.save("s", {"x": np.zeros(1)})
        assert not ck.has("s")


# ---------------------------------------------------------------------------
# checkpointed builds: interruption + resume (S3 + acceptance)
# ---------------------------------------------------------------------------

class TestInterruptAndResume:
    def test_ivf_pq_injected_interrupt_then_resume(self, fresh_res,
                                                   tmp_path):
        from raft_tpu.neighbors import ivf_pq
        rng = np.random.default_rng(0)
        db = rng.standard_normal((512, 32), dtype=np.float32)
        p = ivf_pq.IndexParams(n_lists=16, kmeans_n_iters=2, pq_dim=8)
        ref = ivf_pq.build(fresh_res(), p, db)

        ckdir = str(tmp_path / "pq")
        # kill the build at its first sync point — AFTER the kmeans
        # stage checkpoint is durable (save happens before synchronize)
        with inject("interruptible.synchronize", times=1,
                    exc=InterruptedException):
            with pytest.raises(InterruptedException):
                ivf_pq.build(fresh_res(), p, db, checkpoint=ckdir)
        ck = CheckpointManager(ckdir)
        assert ck.completed == ["kmeans"]

        obs.reset()
        with obs.collecting():
            resumed = ivf_pq.build(fresh_res(), p, db, checkpoint=ckdir,
                                   resume=True)
        c = obs.snapshot()["counters"]
        # completed stage loaded once, NOT recomputed; only the
        # remaining stage checkpointed
        assert c.get("resilience.checkpoint.load") == 1
        assert c.get("resilience.checkpoint.save") == 1
        for leaf in ("centers", "codebooks", "list_codes", "list_indices",
                     "list_sizes"):
            np.testing.assert_array_equal(
                np.asarray(getattr(ref, leaf)),
                np.asarray(getattr(resumed, leaf)), err_msg=leaf)

    def test_cagra_thread_cancel_then_resume(self, fresh_res, tmp_path):
        from raft_tpu.neighbors import cagra
        rng = np.random.default_rng(0)
        db = rng.standard_normal((256, 16), dtype=np.float32)
        p = cagra.IndexParams(intermediate_graph_degree=16, graph_degree=8)
        ref = cagra.build(fresh_res(), p, db)

        ckdir = str(tmp_path / "cg")
        box = {}
        started, go = threading.Event(), threading.Event()

        def worker():
            box["tid"] = threading.get_ident()
            started.set()
            go.wait()
            try:
                cagra.build(fresh_res(), p, db, checkpoint=ckdir)
                box["err"] = None
            except InterruptedException as e:
                box["err"] = e

        t = threading.Thread(target=worker)
        t.start()
        started.wait()
        # cancel from THIS thread before the build reaches its first
        # sync point: deterministic interruption at that point
        interruptible.get_token(box["tid"]).cancel()
        go.set()
        t.join(60)
        assert isinstance(box["err"], InterruptedException)
        ck = CheckpointManager(ckdir)
        assert ck.completed == ["knn_graph"]

        obs.reset()
        with obs.collecting():
            resumed = cagra.build(fresh_res(), p, db, checkpoint=ckdir,
                                  resume=True)
        timers = obs.snapshot()["timers"]
        # the kNN stage was NOT redone (its stage timer never ran);
        # pruning was
        assert "cagra.build.knn_exact" not in timers
        assert "cagra.build.prune" in timers
        np.testing.assert_array_equal(np.asarray(ref.graph),
                                      np.asarray(resumed.graph))

    def test_resume_from_complete_checkpoint_is_bit_identical(
            self, fresh_res, tmp_path):
        from raft_tpu.neighbors import ivf_pq
        rng = np.random.default_rng(0)
        db = rng.standard_normal((512, 32), dtype=np.float32)
        p = ivf_pq.IndexParams(n_lists=16, kmeans_n_iters=2, pq_dim=8)
        ckdir = str(tmp_path / "pq_full")
        full = ivf_pq.build(fresh_res(), p, db, checkpoint=ckdir)
        resumed = ivf_pq.build(fresh_res(), p, db, checkpoint=ckdir,
                               resume=True)
        np.testing.assert_array_equal(np.asarray(full.list_codes),
                                      np.asarray(resumed.list_codes))
        np.testing.assert_array_equal(np.asarray(full.codebooks),
                                      np.asarray(resumed.codebooks))


# ---------------------------------------------------------------------------
# distributed: retry-recovery acceptance + degraded search
# ---------------------------------------------------------------------------

@pytest.fixture
def session(mesh8):
    from raft_tpu.comms import CommsSession
    s = CommsSession(mesh=mesh8, axis_name="data").init()
    yield s
    s.destroy()


@pytest.fixture
def handle(session):
    return session.worker_handle(seed=0)


@pytest.fixture
def dist_index(handle):
    from raft_tpu.distributed import ann
    from raft_tpu.neighbors import ivf_pq
    rng = np.random.default_rng(0)
    db = rng.standard_normal((1024, 32), dtype=np.float32)
    q = rng.standard_normal((16, 32), dtype=np.float32)
    p = ivf_pq.IndexParams(n_lists=8, kmeans_n_iters=2, pq_dim=8)
    return ann, ivf_pq, ann.build(handle, p, db), q


class TestDistributedResilience:
    def test_transient_search_fault_retried_identically(self, handle,
                                                        dist_index):
        ann, ivf_pq, idx, q = dist_index
        sp = ivf_pq.SearchParams(n_probes=4)
        d0, i0 = ann.search(handle, sp, idx, q, 5)
        obs.reset()
        with obs.collecting():
            with inject("distributed.ann.search", times=1,
                        exc=TransientFault):
                d1, i1 = ann.search(handle, sp, idx, q, 5)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_allclose(np.asarray(d0), np.asarray(d1))
        c = obs.snapshot()["counters"]
        assert c.get(
            "resilience.fault.injected.distributed.ann.search") == 1
        assert c.get("resilience.retry.distributed.ann.search") == 1
        assert "resilience.giveup.distributed.ann.search" not in c

    def test_transient_fault_retried_at_fused_operating_point(
            self, handle, dist_index):
        """Round-7 CI operating point: scan_mode="fused" through the
        sharded path — retried faults replay identically, and the
        documented shard_map lowering (traceable probe-order recon) is
        visible as fused_fallback counter ticks."""
        ann, ivf_pq, idx, q = dist_index
        sp = ivf_pq.SearchParams(n_probes=4, scan_mode="fused",
                                 per_probe_topk=4)
        d0, i0 = ann.search(handle, sp, idx, q, 5)
        obs.reset()
        with obs.collecting():
            with inject("distributed.ann.search", times=1,
                        exc=TransientFault):
                d1, i1 = ann.search(handle, sp, idx, q, 5)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_allclose(np.asarray(d0), np.asarray(d1))
        c = obs.snapshot()["counters"]
        assert c.get(
            "resilience.fault.injected.distributed.ann.search") == 1
        assert c.get("ivf_pq.search.fused_fallback", 0) >= 1

    def test_degraded_search_masks_failed_shards(self, handle, dist_index):
        ann, ivf_pq, idx, q = dist_index
        sp = ivf_pq.SearchParams(n_probes=4)
        per = 1024 // 8
        with inject() as plan:
            plan.fail_shards(1)
            d, i, status = ann.search(handle, sp, idx, q, 5,
                                      return_status=True)
        assert list(np.asarray(status)) == [1, 0, 1, 1, 1, 1, 1, 1]
        ids = np.asarray(i)
        assert not ((ids >= per) & (ids < 2 * per)).any()

    def test_straggler_injected_search_merges_exact(self, handle,
                                                    dist_index, monkeypatch):
        """A straggler-injected sharded search still merges EXACT results
        — the slow shard eventually answers, only latency moves — and the
        pause + per-shard delay vector land in the flight recorder."""
        from raft_tpu.observability import flight
        slept = []
        monkeypatch.setattr(faults, "_sleep", slept.append)
        ann, ivf_pq, idx, q = dist_index
        sp = ivf_pq.SearchParams(n_probes=8)
        d0, i0 = ann.search(handle, sp, idx, q, 5)
        flight.clear()
        plan = FaultPlan(seed=1).straggle_shard(2, delay=0.05, jitter=0.01)
        with plan.active():
            d1, i1 = ann.search(handle, sp, idx, q, 5)
        np.testing.assert_array_equal(np.asarray(i0), np.asarray(i1))
        np.testing.assert_allclose(np.asarray(d0), np.asarray(d1))
        assert slept and all(0.05 <= s <= 0.06 for s in slept)
        evs = flight.events("distributed.straggler")
        assert len(evs) == 1
        delays = evs[0]["attrs"]["delays_s"]
        assert evs[0]["attrs"]["n_shards"] == 8
        assert delays[2] > 0.0 and delays[0] == 0.0

    def test_degraded_search_explicit_flags(self, handle, dist_index):
        ann, ivf_pq, idx, q = dist_index
        sp = ivf_pq.SearchParams(n_probes=4)
        d, i, status = ann.search(handle, sp, idx, q, 5,
                                  failed_shards=[0, 7],
                                  return_status=True)
        assert list(np.asarray(status)) == [0, 1, 1, 1, 1, 1, 1, 0]

    def test_degraded_search_lands_flight_event(self, handle, dist_index):
        """Every degraded dispatch records an always-on flight event
        (anomaly forensics do not depend on tracing being enabled), and
        under tracing the ambient trace carries the host-static shard
        status vector with no extra device->host sync."""
        from raft_tpu.observability import flight, trace
        ann, ivf_pq, idx, q = dist_index
        sp = ivf_pq.SearchParams(n_probes=4)
        flight.clear()
        trace.enable_tracing()
        try:
            rec = trace.start_request()
            with trace.activating(rec):
                ann.search(handle, sp, idx, q, 5, failed_shards=[2, 5])
        finally:
            trace.disable_tracing()
        evs = flight.events("distributed.degraded_search")
        assert len(evs) == 1
        assert sorted(evs[0]["attrs"]["failed"]) == [2, 5]
        assert evs[0]["attrs"]["n_shards"] == 8
        assert evs[0]["trace_id"] == rec.trace_id
        status = rec.attrs["distributed.shard_status"]
        assert status[2] == 0 and status[5] == 0 and status[0] == 1

    def test_all_shards_failed_is_fully_padded(self, handle, dist_index):
        ann, ivf_pq, idx, q = dist_index
        sp = ivf_pq.SearchParams(n_probes=4)
        d, i, status = ann.search(handle, sp, idx, q, 5,
                                  failed_shards=range(8),
                                  return_status=True)
        assert (np.asarray(i) == -1).all()
        assert (np.asarray(status) == 0).all()

    def test_search_deadline_gives_up(self, handle, dist_index):
        ann, ivf_pq, idx, q = dist_index
        sp = ivf_pq.SearchParams(n_probes=4)
        with pytest.raises(DeadlineExceededError):
            ann.search(handle, sp, idx, q, 5, deadline=Deadline(0.0))

    def test_build_entry_retried(self, session):
        from raft_tpu.distributed import ann
        from raft_tpu.neighbors import ivf_pq
        handle = session.worker_handle(seed=0)
        rng = np.random.default_rng(1)
        db = rng.standard_normal((512, 16), dtype=np.float32)
        p = ivf_pq.IndexParams(n_lists=8, kmeans_n_iters=2, pq_dim=4)
        obs.reset()
        with obs.collecting():
            with inject("distributed.ann.build", times=1,
                        exc=TransientFault):
                idx = ann.build(handle, p, db)
        assert idx.size == 512
        c = obs.snapshot()["counters"]
        assert c.get("resilience.retry.distributed.ann.build") == 1


# ---------------------------------------------------------------------------
# zero-overhead contract
# ---------------------------------------------------------------------------

class TestZeroOverhead:
    def test_no_plan_no_collection_records_nothing(self, res):
        from raft_tpu.neighbors import ivf_flat
        rng = np.random.default_rng(0)
        db = rng.standard_normal((256, 16), dtype=np.float32)
        idx = ivf_flat.build(res, ivf_flat.IndexParams(n_lists=8,
                                                       kmeans_n_iters=2),
                             db)
        obs.reset()
        assert not obs.enabled() and not faults.is_active()
        ivf_flat.search(res, ivf_flat.SearchParams(n_probes=4), idx,
                        db[:4], 5)
        snap = obs.snapshot()
        assert snap["counters"] == {} and snap["timers"] == {}
