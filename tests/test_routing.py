"""Load-aware replica routing (PR 18) — the policy that turns PR 17's
replicas from failover spares into a throughput / tail-latency lever.

Unit half: greedy least-loaded plan over the replica ranks (keeps the
``healthy_routing`` keep-primary-when-uncovered contract), the probe
heat window (lazy observe / maintenance-path refresh / decayed read),
the load-score formula terms, and the overload evidence folding through
the health tracker.  Integration half (8-device mesh): policy-routed
search is BIT-IDENTICAL at full probe, spreads lists across replica
ranks with zero steady-state recompiles while the tables update, a
hedge re-issues to the *least-loaded* covering replica, a load-SUSPECT
shard is never double-counted as failed in the status vector, and the
probe-frequency-aware rebalance separates synthetically hot co-located
lists.
"""

import jax
import numpy as np
import pytest

from raft_tpu import observability as obs
from raft_tpu.core.error import RaftError
from raft_tpu.distributed import ann
from raft_tpu.distributed.health import (
    HealthConfig,
    HealthTracker,
    SUSPECT,
)
from raft_tpu.distributed.routing import RoutingConfig, RoutingPolicy
from raft_tpu.neighbors import ivf_pq
from raft_tpu.observability import flight


class _StubTracker:
    """Minimal tracker double: fixed penalties in, overload evidence
    recorded out — isolates the policy's score math from the real
    state machine (which tests/test_health.py owns)."""

    def __init__(self, n, penalties=None):
        self._pen = list(penalties if penalties is not None
                         else [0.0] * n)
        self.overloads = []

    def load_penalties(self):
        return tuple(self._pen)

    def note_overload(self, shard, load):
        self.overloads.append((int(shard), float(load)))


# ---------------------------------------------------------------------------
# config


class TestRoutingConfig:
    def test_defaults_validate(self):
        cfg = RoutingConfig()
        assert cfg.validate() is cfg

    @pytest.mark.parametrize("kw", [dict(ewma_alpha=0.0),
                                    dict(ewma_alpha=1.5),
                                    dict(window_slots=0),
                                    dict(window_decay=0.0),
                                    dict(max_pending=0),
                                    dict(overload_factor=0.5),
                                    dict(hot_bucket_rows=-1)])
    def test_rejects_bad_knobs(self, kw):
        with pytest.raises(RaftError):
            RoutingConfig(**kw).validate()

    def test_policy_rejects_empty(self):
        with pytest.raises(RaftError):
            RoutingPolicy(0)


# ---------------------------------------------------------------------------
# the plan (pure host — no mesh)


class TestPlanUnit:
    NL = 32

    def _placement(self, r=2, seed=11):
        sizes = np.random.default_rng(seed).integers(5, 200, self.NL)
        return ann.compute_placement(sizes, 8, replication_factor=r)

    def test_r1_plan_is_the_primary_tables(self):
        p = self._placement(r=1)
        pol = RoutingPolicy(8)
        eo, es = pol.plan(p)
        np.testing.assert_array_equal(eo, p.owner)
        np.testing.assert_array_equal(es, p.local_slot)

    def test_plan_routes_only_to_real_owners(self):
        p = self._placement(r=2)
        pol = RoutingPolicy(8)
        eo, es = pol.plan(p)
        owners, slots = p.rank_tables()
        for g in range(self.NL):
            rank = np.nonzero(owners[:, g] == eo[g])[0]
            assert rank.size == 1, f"list {g} routed to a non-owner"
            assert es[g] == slots[rank[0], g]

    def test_plan_spreads_and_balances(self):
        # greedy LPT over both ranks must use rank 1 and end at least
        # as balanced (by planned weight) as primary-only routing
        p = self._placement(r=2)
        pol = RoutingPolicy(8)
        eo, _ = pol.plan(p)
        choice = pol.choice_summary()
        assert choice["per_rank_lists"][1] > 0
        assert sum(choice["per_rank_lists"]) == self.NL
        w = np.full(self.NL, 1.0 / self.NL)   # fresh policy: uniform
        routed = np.bincount(eo, weights=w, minlength=8)
        primary = np.bincount(np.asarray(p.owner), weights=w,
                              minlength=8)
        assert routed.max() <= primary.max() + 1e-12

    def test_down_shard_excluded_and_covered(self):
        p = self._placement(r=2)
        pol = RoutingPolicy(8)
        eo, _ = pol.plan(p, down=(3,))
        assert 3 not in set(eo.tolist())
        assert pol.choice_summary()["down"] == [3]

    def test_uncovered_list_keeps_rank0_primary(self):
        # both owners of a list down -> plan keeps the primary (same
        # contract as healthy_routing: degraded masking owns it)
        p = self._placement(r=2)
        owners, _ = p.rank_tables()
        g = 0
        down = tuple(int(owners[j, g]) for j in range(2))
        pol = RoutingPolicy(8)
        eo, es = pol.plan(p, down=down)
        assert eo[g] == p.owner[g]
        assert es[g] == p.local_slot[g]

    def test_hedge_prefers_least_loaded_covering_replica(self):
        # satellite: the down (straggling) shard's lists must re-issue
        # to the covering replica with the LOWEST load score, not
        # blindly the lowest rank — penalize one covering shard and
        # every choice must avoid it (r=3: always an alternative)
        p = self._placement(r=3)
        owners, _ = p.rank_tables()
        s = int(p.owner[0])                   # the straggler
        mine = np.nonzero(np.asarray(p.owner) == s)[0]
        pen_shard = int(owners[1, mine[0]])   # covers some of s's lists
        pen = [0.0] * 8
        pen[pen_shard] = 10.0                 # 1024 rows/unit >> weights
        pol = RoutingPolicy(8, tracker=_StubTracker(8, pen))
        eo, _ = pol.plan(p, down=(s,))
        for g in mine:
            assert eo[g] != s
            assert eo[g] != pen_shard, (
                f"list {g} hedged onto the loaded replica "
                f"{pen_shard} over {owners[:, g]}")

    def test_load_scores_use_tracker_penalties(self):
        pen = [0.0, 2.0, 0.0, 0.0, 0.0, 0.0, 0.0, 0.0]
        pol = RoutingPolicy(8, tracker=_StubTracker(8, pen))
        scores = pol.shard_scores()
        assert scores[1] == pytest.approx(
            pol.config.penalty_rows * 2.0)
        assert scores[0] == 0.0

    def test_overload_evidence_routes_through_tracker(self):
        # heat concentrated on one shard's lists drives its EWMA rows
        # past overload_factor x mean -> note_overload fires with the
        # ratio, and the mutation never touches tracker state directly
        p = self._placement(r=1)
        s = int(p.owner[0])
        mine = np.nonzero(np.asarray(p.owner) == s)[0]
        hist = np.zeros(self.NL)
        hist[mine] = 1000.0
        tr = _StubTracker(8)
        pol = RoutingPolicy(8, tracker=tr)
        pol.observe_probes(hist)
        assert pol.refresh() == 1
        for _ in range(8):
            pol.plan(p)
        assert tr.overloads, "hot shard never reported"
        shard, ratio = tr.overloads[-1]
        assert shard == s
        assert ratio > pol.config.overload_factor


class TestProbeWindow:
    def test_refresh_empty_is_noop(self):
        pol = RoutingPolicy(4)
        assert pol.refresh() == 0
        assert pol.expected_probe_load() is None

    def test_window_normalizes_and_decays(self):
        pol = RoutingPolicy(4, RoutingConfig(window_decay=0.5))
        pol.observe_probes(np.array([10.0, 0.0, 0.0, 0.0]))
        assert pol.refresh() == 1
        pol.observe_probes(np.array([0.0, 10.0, 0.0, 0.0]))
        assert pol.refresh() == 1
        heat = pol.expected_probe_load()
        assert heat.sum() == pytest.approx(1.0)
        # newest slot carries weight 1.0, the older one decay=0.5
        assert heat[1] == pytest.approx(2.0 / 3.0)
        assert heat[0] == pytest.approx(1.0 / 3.0)

    def test_window_slots_bounded(self):
        pol = RoutingPolicy(2, RoutingConfig(window_slots=2))
        for _ in range(5):
            pol.observe_probes(np.ones(2))
            pol.refresh()
        assert pol.stats()["window_slots"] == 2

    def test_pending_bounded_without_refresh(self):
        pol = RoutingPolicy(2, RoutingConfig(max_pending=3))
        for _ in range(10):
            pol.observe_probes(np.ones(2))
        assert pol.stats()["pending_batches"] == 3

    def test_spread_bucket_map(self):
        pol = RoutingPolicy(4, RoutingConfig(hot_bucket_rows=64))
        assert pol.spread_bucket(1)
        assert pol.spread_bucket(64)
        assert not pol.spread_bucket(65)
        assert not pol.spread_bucket(512)


# ---------------------------------------------------------------------------
# heat-weighted LPT (the rebalancer's recompute math)


class TestHeatWeightedPlacement:
    def test_heat_weight_separates_colocated_hot_lists(self):
        # equal sizes: LPT wraps lists round-robin, so lists 0 and 8
        # share shard 0.  Heat-weighted recompute (probe rate x rows,
        # the rebalance_routed formula) makes them the two heaviest
        # and LPT puts them on DIFFERENT shards
        sizes = np.full(16, 100, np.int64)
        p0 = ann.compute_placement(sizes, 8, replication_factor=2)
        assert p0.owner[0] == p0.owner[8]
        heat = np.full(16, 1.0)
        heat[[0, 8]] = 50.0
        heat /= heat.sum()
        weights = np.maximum((sizes * heat * 16).astype(np.int64), 1)
        p1 = ann.compute_placement(weights, 8, replication_factor=2,
                                   generation=p0.generation + 1)
        assert p1.owner[0] != p1.owner[8]
        # anti-co-location still holds for each hot list's own replicas
        for g in (0, 8):
            assert len(set(p1.owners[:, g].tolist())) == 2


# ---------------------------------------------------------------------------
# integration: the 8-device mesh


class TestRoutedSearchWithPolicy:
    """Mesh half: mirrors ``TestReplicatedRouted``'s fixtures — the
    policy must compose with the PR 17 failover/hedging machinery
    without changing one bit of any answer."""

    N, DIM, NL, NQ, K = 2048, 32, 32, 16, 10

    @pytest.fixture(scope="class")
    def rhandle(self):
        devs = jax.devices()
        if len(devs) < 8:
            devs = jax.devices("cpu")
        if len(devs) < 8:
            pytest.skip("needs 8 devices")
        from raft_tpu.comms import CommsSession
        mesh = jax.sharding.Mesh(np.asarray(devs[:8]), ("data",))
        s = CommsSession(mesh=mesh, axis_name="data").init()
        yield s.worker_handle(seed=0)
        s.destroy()

    @pytest.fixture(scope="class")
    def data(self):
        rng = np.random.default_rng(7)
        db = rng.normal(size=(self.N, self.DIM)).astype(np.float32)
        q = rng.normal(size=(self.NQ, self.DIM)).astype(np.float32)
        return db, q

    @pytest.fixture(scope="class")
    def built(self, rhandle, data):
        db, _ = data
        params = ivf_pq.IndexParams(n_lists=self.NL, pq_dim=8,
                                    kmeans_n_iters=3,
                                    cache_reconstructions=True)
        base = ivf_pq.build(rhandle, params, db)
        return (base, ann.shard_by_list(rhandle, base,
                                        replication_factor=2))

    @pytest.fixture(scope="class")
    def r3(self, rhandle, built):
        base, _ = built
        return ann.shard_by_list(rhandle, base, replication_factor=3)

    def _policy(self, tracker=None, **kw):
        return RoutingPolicy(8, RoutingConfig(**kw) if kw else None,
                             tracker=tracker)

    # ---- bit-identity + the flight trail ---------------------------------

    def test_policy_routed_bit_identical_full_probe(self, rhandle, data,
                                                    built):
        _, q = data
        _, r2 = built
        sp = ivf_pq.SearchParams(n_probes=self.NL)
        d0, i0 = ann.search(rhandle, sp, r2, q, self.K)
        pol = self._policy()
        flight.clear()
        with obs.collecting():
            c0 = obs.registry().counter("distributed.replica_choice").value
            d1, i1 = ann.search(rhandle, sp, r2, q, self.K, routing=pol)
            c1 = obs.registry().counter("distributed.replica_choice").value
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))
        assert c1 == c0 + 1
        # the healthy plan really used rank 1 (replicas paying rent)
        choice = pol.choice_summary()
        assert choice["per_rank_lists"][1] > 0
        assert choice["down"] == []
        evs = flight.events("distributed.replica_choice")
        assert evs and evs[0]["attrs"]["reason"] == "load_spread"
        assert evs[0]["attrs"]["per_rank_lists"] == \
            choice["per_rank_lists"]

    def test_policy_routed_fused_bit_identical(self, rhandle, data,
                                               built):
        _, q = data
        _, r2 = built
        sp = ivf_pq.SearchParams(n_probes=self.NL, scan_mode="fused")
        d0, i0 = ann.search(rhandle, sp, r2, q, self.K)
        d1, i1 = ann.search(rhandle, sp, r2, q, self.K,
                            routing=self._policy())
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))

    def test_failover_with_policy_bit_identical(self, rhandle, data,
                                                built):
        _, q = data
        _, r2 = built
        sp = ivf_pq.SearchParams(n_probes=self.NL)
        d0, i0 = ann.search(rhandle, sp, r2, q, self.K)
        pol = self._policy()
        flight.clear()
        d1, i1, st = ann.search(rhandle, sp, r2, q, self.K,
                                failed_shards=(2,), routing=pol,
                                return_status=True)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))
        st = np.asarray(st)
        assert st[2] == ann.SHARD_REPLICA_SERVED
        assert not np.any(st == ann.SHARD_FAILED)
        evs = flight.events("distributed.replica_choice")
        assert evs and evs[0]["attrs"]["reason"] == "failover"

    # ---- zero recompiles while the tables update -------------------------

    def test_zero_recompiles_while_tables_update(self, rhandle, data,
                                                 built):
        _, q = data
        _, r2 = built
        sp = ivf_pq.SearchParams(n_probes=8)
        tr = HealthTracker(8, HealthConfig(suspect_after=100))
        pol = self._policy(tracker=tr)
        ann.search(rhandle, sp, r2, q, self.K, routing=pol)   # warm
        with obs.collecting():
            c0 = obs.registry().counter("xla.compiles").value
            for step in range(4):
                # every step shifts the scores (EWMA folds + a fresh
                # tracker penalty) -> new effective tables, same shapes
                tr.note_overload(step % 8, 3.0)
                ann.search(rhandle, sp, r2, q, self.K, routing=pol)
            c1 = obs.registry().counter("xla.compiles").value
        assert c1 == c0, f"{c1 - c0} recompiles from table updates"

    # ---- hedging: least-loaded replica (satellite) -----------------------

    def test_hedge_reissues_to_least_loaded_replica(self, rhandle, data,
                                                    r3, monkeypatch):
        """A straggler's lists must re-issue to the covering replica
        with the lowest load score (r=3: two candidates each), the
        answer stays bit-identical and the wait collapses to the
        deadline."""
        from raft_tpu.resilience import FaultPlan, faults
        slept = []
        monkeypatch.setattr(faults, "_sleep", slept.append)
        _, q = data
        sp = ivf_pq.SearchParams(n_probes=self.NL)
        d0, i0 = ann.search(rhandle, sp, r3, q, self.K)
        owners, _ = r3.placement.rank_tables()
        s = int(r3.placement.owner[0])          # the straggler
        mine = np.nonzero(np.asarray(r3.placement.owner) == s)[0]
        pen_shard = int(owners[1, mine[0]])     # a covering replica
        pen = [0.0] * 8
        pen[pen_shard] = 10.0
        pol = self._policy(tracker=_StubTracker(8, pen))
        plans = []
        orig = pol.plan
        monkeypatch.setattr(
            pol, "plan",
            lambda p, down=(): plans.append((tuple(down), orig(p, down)))
            or plans[-1][1])
        flight.clear()
        plan = FaultPlan(seed=3).straggle_shard(s, delay=0.5)
        with plan.active():
            d1, i1, st = ann.search(rhandle, sp, r3, q, self.K,
                                    shard_deadline_s=0.05,
                                    routing=pol, return_status=True)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))
        assert slept == [0.05], slept
        assert np.asarray(st)[s] == ann.SHARD_REPLICA_SERVED
        down, (eo, _) = plans[-1]
        assert down == (s,)
        for g in mine:
            assert eo[g] != s
            assert eo[g] != pen_shard, (
                f"hedge sent list {g} to the loaded replica")
        evs = flight.events("distributed.replica_choice")
        assert evs and evs[-1]["attrs"]["reason"] == "hedge"
        assert flight.events("distributed.hedged_read")

    def test_load_suspect_not_counted_failed_in_status(self, rhandle,
                                                       data, built):
        """Satellite: a shard demoted to SUSPECT by pure load evidence
        is hedge-able but NOT failed — the status vector must report it
        replica-served (or plain OK), never SHARD_FAILED, and the
        tracker must keep it out of failed_shards()."""
        _, q = data
        _, r2 = built
        tr = HealthTracker(8, HealthConfig(suspect_after=2))
        for _ in range(4):
            tr.note_overload(3, 5.0)
        assert tr.states()[3] == SUSPECT
        assert tr.failed_shards() == ()
        sp = ivf_pq.SearchParams(n_probes=self.NL)
        d0, i0 = ann.search(rhandle, sp, r2, q, self.K)
        pol = self._policy(tracker=tr)
        d1, i1, st = ann.search(rhandle, sp, r2, q, self.K, health=tr,
                                routing=pol, return_status=True)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))
        st = np.asarray(st)
        assert not np.any(st == ann.SHARD_FAILED)
        assert st[3] == ann.SHARD_REPLICA_SERVED   # hedged, not dead

    # ---- probe-frequency accumulation + heat-aware rebalance -------------

    def test_dispatch_observes_probes_lazily(self, rhandle, data, built):
        _, q = data
        _, r2 = built
        sp = ivf_pq.SearchParams(n_probes=4)
        pol = self._policy()
        for _ in range(3):
            ann.search(rhandle, sp, r2, q, self.K, routing=pol)
        assert pol.stats()["pending_batches"] == 3
        assert pol.refresh() == 3
        heat = pol.expected_probe_load()
        assert heat.shape == (self.NL,)
        assert heat.sum() == pytest.approx(1.0)
        # 4 of 32 lists probed per query -> heat is concentrated
        assert np.count_nonzero(heat) < self.NL

    def test_heat_aware_rebalance_separates_hot_lists(self, rhandle,
                                                      data, built):
        """Acceptance: feed the policy a synthetic probe histogram
        concentrated on two lists co-located on one primary shard; the
        probe-frequency-aware rebalance must become eligible on heat
        skew alone and the recomputed placement must pull the hot
        pair's primaries apart — without changing one bit of the
        answers."""
        from raft_tpu.serving import rebalancer
        _, q = data
        _, r2 = built
        own = np.asarray(r2.placement.owner)
        s = int(np.argmax(np.bincount(own, minlength=8)))
        g1, g2 = np.nonzero(own == s)[0][:2]
        hist = np.ones(self.NL)
        hist[[g1, g2]] = 5000.0
        pol = self._policy()
        pol.observe_probes(hist)
        sp = ivf_pq.SearchParams(n_probes=self.NL)
        d0, i0 = ann.search(rhandle, sp, r2, q, self.K)
        cand = rebalancer.rebalance_routed(rhandle, r2, routing=pol)
        assert cand is not r2, "heat skew did not make the pass eligible"
        assert cand.placement.generation == r2.placement.generation + 1
        new_own = np.asarray(cand.placement.owner)
        assert new_own[g1] != new_own[g2], (
            "hot lists still co-located after heat-aware rebalance")
        d1, i1 = ann.search(rhandle, sp, cand, q, self.K)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))
        # the pass re-seeded the policy's expected-work rows from the
        # new placement
        assert pol.stats()["pending_batches"] == 0
