"""Test configuration.

Mirrors the reference's multi-GPU-without-a-cluster strategy (SURVEY.md §4:
raft-dask's LocalCUDACluster fixture): tests run on a virtual 8-device CPU
backend so sharded/mesh code paths execute exactly as they would across a TPU
slice, without hardware.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402

# A sitecustomize hook on this machine imports jax at interpreter startup
# (registering the TPU-tunnel plugin), so the env mutations above can be too
# late — jax.config snapshots JAX_PLATFORMS at import.  config.update works
# post-import; XLA_FLAGS is read later, at first backend init, so the env
# var set above still provides the 8 virtual CPU devices.
jax.config.update("jax_platforms", "cpu")
# persistent compilation cache: jit compiles dominate suite runtime on the
# CPU box; cache hits cut repeat runs to seconds
jax.config.update("jax_compilation_cache_dir",
                  os.environ.get("JAX_COMPILATION_CACHE_DIR",
                                 "/tmp/raft_tpu_jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running; the fast gate tier runs with -m 'not slow'")


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    """On test failure, dump the flight recorder if RAFT_TPU_FLIGHT_DUMP
    is set (CI exports it so the Chrome-trace forensics ride the failure
    artifact).  No-op — not even an env read — on passing tests."""
    outcome = yield
    report = outcome.get_result()
    if report.when == "call" and report.failed:
        from raft_tpu.observability import flight
        path = flight.maybe_auto_dump(f"test_failure:{item.nodeid}")
        if path:
            tr = item.config.pluginmanager.get_plugin("terminalreporter")
            if tr is not None:
                tr.write_line(f"flight dump: {path}")


@pytest.fixture
def res():
    from raft_tpu import DeviceResources
    return DeviceResources(seed=42)


@pytest.fixture
def mesh8():
    devs = jax.devices()
    if len(devs) < 8:
        # the axon tunnel exposes one real TPU; fall back to the virtual
        # 8-device CPU backend for mesh tests
        devs = jax.devices("cpu")
    if len(devs) < 8:
        pytest.skip("needs 8 devices (set "
                    "XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    return jax.sharding.Mesh(np.asarray(devs[:8]), ("data",))
