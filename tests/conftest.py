"""Test configuration.

Mirrors the reference's multi-GPU-without-a-cluster strategy (SURVEY.md §4:
raft-dask's LocalCUDACluster fixture): tests run on a virtual 8-device CPU
backend so sharded/mesh code paths execute exactly as they would across a TPU
slice, without hardware.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402
import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def res():
    from raft_tpu import DeviceResources
    return DeviceResources(seed=42)


@pytest.fixture
def mesh8():
    devs = jax.devices()
    if len(devs) < 8:
        # the axon tunnel exposes one real TPU; fall back to the virtual
        # 8-device CPU backend for mesh tests
        devs = jax.devices("cpu")
    if len(devs) < 8:
        pytest.skip("needs 8 devices (set "
                    "XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    return jax.sharding.Mesh(np.asarray(devs[:8]), ("data",))
