"""IVF-Flat tests — recall-based, mirroring the reference's ANN test pattern
(cpp/test/neighbors/ann_ivf_flat.cuh: ground truth from naive_knn, assertion
``eval_neighbours(min_recall)``), plus serialization round-trip in-test.
"""

import io

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors import ivf_flat
from raft_tpu.random import make_blobs


def naive_knn(db, q, k):
    d = ((q[:, None, :] - db[None, :, :]) ** 2).sum(-1)
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, axis=1), idx


def recall(found, truth):
    hits = sum(len(set(f) & set(t)) for f, t in zip(found, truth))
    return hits / truth.size


@pytest.fixture(scope="module")
def dataset():
    X, _ = make_blobs(4000, 16, n_clusters=50, cluster_std=1.0, seed=0)
    db = np.asarray(X[:3800])
    q = np.asarray(X[3800:3850])
    return db, q


class TestIvfFlat:
    def test_build_shapes(self, res, dataset):
        db, _ = dataset
        params = ivf_flat.IndexParams(n_lists=32, kmeans_n_iters=10)
        index = ivf_flat.build(res, params, db)
        assert index.n_lists == 32
        assert index.dim == db.shape[1]
        assert index.size == db.shape[0]
        assert index.capacity % 32 == 0
        # every row landed exactly once
        ids = np.asarray(index.list_indices)
        valid = ids[ids >= 0]
        assert sorted(valid.tolist()) == list(range(db.shape[0]))

    def test_search_recall(self, res, dataset):
        db, q = dataset
        params = ivf_flat.IndexParams(n_lists=32, kmeans_n_iters=10)
        index = ivf_flat.build(res, params, db)
        d, i = ivf_flat.search(res, ivf_flat.SearchParams(n_probes=8),
                               index, q, 10)
        _, ti = naive_knn(db, q, 10)
        assert recall(np.asarray(i), ti) > 0.9

    def test_full_probe_is_exact(self, res, dataset):
        db, q = dataset
        params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=10)
        index = ivf_flat.build(res, params, db)
        d, i = ivf_flat.search(res, ivf_flat.SearchParams(n_probes=16),
                               index, q, 10)
        td, ti = naive_knn(db, q, 10)
        assert recall(np.asarray(i), ti) > 0.99
        np.testing.assert_allclose(np.asarray(d), td, rtol=1e-3, atol=1e-2)

    def test_extend(self, res, dataset):
        db, q = dataset
        params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=5,
                                      add_data_on_build=False)
        index = ivf_flat.build(res, params, db)
        assert index.size == 0
        index = ivf_flat.extend(res, index, db[:2000],
                                jnp.arange(2000, dtype=jnp.int32))
        index = ivf_flat.extend(
            res, index, db[2000:],
            jnp.arange(2000, db.shape[0], dtype=jnp.int32))
        assert index.size == db.shape[0]
        _, i = ivf_flat.search(res, ivf_flat.SearchParams(n_probes=16),
                               index, q, 10)
        _, ti = naive_knn(db, q, 10)
        assert recall(np.asarray(i), ti) > 0.99

    def test_extend_fast_path_appends_in_place(self, res, dataset):
        """A small extend into lists with headroom must keep the capacity
        (the O(n_new) scatter-append path) and stay exact."""
        db, q = dataset
        params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=5)
        index = ivf_flat.build(res, params, db[:3000])
        cap0 = index.capacity
        # capacity is rounded up to _LIST_ALIGN, so a handful of rows fits
        index = ivf_flat.extend(res, index, db[3000:3040],
                                jnp.arange(3000, 3040, dtype=jnp.int32))
        assert index.capacity == cap0        # fast path: no repack
        assert index.size == 3040
        ids = np.asarray(index.list_indices)
        valid = ids[ids >= 0]
        assert sorted(valid.tolist()) == list(range(3040))
        _, i = ivf_flat.search(res, ivf_flat.SearchParams(n_probes=16),
                               index, q, 10)
        _, ti = naive_knn(db[:3040], q, 10)
        assert recall(np.asarray(i), ti) > 0.99

    def test_grouped_scan_matches_probe_order_scan(self, res, dataset):
        """List-centric grouped scan vs probe-order scan: IVF-Flat distances
        are exact fp32, so results must agree to fp tolerance."""
        db, q = dataset
        params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=10)
        index = ivf_flat.build(res, params, db)
        from raft_tpu.neighbors import grouped
        probes = ivf_flat._select_clusters(index.centers, jnp.asarray(q),
                                           8, index.metric)
        n_groups = grouped.round_groups(
            int(grouped.num_groups(probes, index.n_lists)))
        d1, i1 = ivf_flat._search_impl(
            index.centers, index.list_data, index.list_indices,
            jnp.asarray(q), 10, 8, index.metric)
        d2, i2 = ivf_flat._search_impl_grouped(
            index.centers, index.list_data, index.list_indices,
            jnp.asarray(q), probes, 10, index.metric, n_groups, 16)
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                                   rtol=1e-4, atol=1e-3)
        overlap = np.mean([len(set(a) & set(b)) / 10
                           for a, b in zip(np.asarray(i1), np.asarray(i2))])
        assert overlap > 0.99

    def test_pallas_flat_scan_matches_xla_scan(self, res):
        """The fused Pallas flat-scan kernel (interpret mode on CPU) must
        agree with the XLA grouped scan — IVF-Flat distances are exact
        fp32, so values match to fp tolerance."""
        from raft_tpu.neighbors import grouped
        rng = np.random.default_rng(4)
        db = rng.normal(size=(2000, 128)).astype(np.float32)
        q = rng.normal(size=(32, 128)).astype(np.float32)
        params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=5)
        index = ivf_flat.build(res, params, db)
        probes = ivf_flat._select_clusters(index.centers, jnp.asarray(q),
                                           8, index.metric)
        n_groups = grouped.round_groups(
            int(grouped.num_groups(probes, index.n_lists)))
        args = (index.centers, index.list_data, index.list_indices,
                jnp.asarray(q), probes, 10, index.metric, n_groups, 16)
        d1, i1 = ivf_flat._search_impl_grouped(*args)
        d2, i2 = ivf_flat._search_impl_grouped(
            *args, use_pallas=True, pallas_interpret=True)
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                                   rtol=1e-4, atol=1e-3)
        overlap = np.mean([len(set(a) & set(b)) / 10
                           for a, b in zip(np.asarray(i1), np.asarray(i2))])
        assert overlap > 0.99

    def test_pallas_scan_large_k(self, res):
        """k=100 exercises the fori_loop extraction variant (kt > 64 —
        the radix-select regime, reference select_radix.cuh); must match
        the XLA grouped scan."""
        from raft_tpu.neighbors import grouped
        rng = np.random.default_rng(5)
        db = rng.normal(size=(4000, 128)).astype(np.float32)
        q = rng.normal(size=(16, 128)).astype(np.float32)
        params = ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=5)
        index = ivf_flat.build(res, params, db)
        probes = ivf_flat._select_clusters(index.centers, jnp.asarray(q),
                                           4, index.metric)
        n_groups = grouped.round_groups(
            int(grouped.num_groups(probes, index.n_lists)))
        args = (index.centers, index.list_data, index.list_indices,
                jnp.asarray(q), probes, 100, index.metric, n_groups, 16)
        d1, i1 = ivf_flat._search_impl_grouped(*args)
        d2, i2 = ivf_flat._search_impl_grouped(
            *args, use_pallas=True, pallas_interpret=True)
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                                   rtol=1e-4, atol=1e-3)
        overlap = np.mean([len(set(a) & set(b)) / 100
                           for a, b in zip(np.asarray(i1), np.asarray(i2))])
        assert overlap > 0.99

    def test_skewed_batch_exact_at_static_capacity(self, res, dataset):
        """Round 10: the grouped dispatch runs at the static worst-case
        group capacity, so a batch whose probes pile onto one list (the
        case the old host-synced cache re-dispatched for) must come out
        exact on the FIRST dispatch — no host-synced group count exists
        anymore."""
        db, q = dataset
        params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=5)
        index = ivf_flat.build(res, params, db)
        sp = ivf_flat.SearchParams(n_probes=4)
        # batch A: natural queries; batch B: every query near one
        # centroid -> probes pile onto few lists (maximal group skew)
        ivf_flat.search(res, sp, index, q, 10)
        assert not hasattr(index, "_group_cache")  # protocol removed
        hot = np.asarray(index.centers)[3]
        qb = (hot[None, :] +
              0.01 * np.random.default_rng(0).normal(
                  size=(q.shape[0], db.shape[1]))).astype(np.float32)
        d_b, i_b = ivf_flat.search(res, sp, index, qb, 10)
        # exactness: must equal the traceable probe-order scan
        d_ref, i_ref = ivf_flat._search_impl(
            index.centers, index.list_data, index.list_indices,
            jnp.asarray(qb), 10, 4, index.metric)
        np.testing.assert_allclose(np.asarray(d_b), np.asarray(d_ref),
                                   rtol=1e-4, atol=1e-3)
        overlap = np.mean([len(set(a) & set(b)) / 10
                           for a, b in zip(np.asarray(i_b),
                                           np.asarray(i_ref))])
        assert overlap > 0.99

    def test_search_inside_jit(self, res, dataset):
        """search() must stay traceable under an outer jit (the grouped
        dispatch host-syncs, so tracing falls back to the probe-order
        scan) and agree with the eager result."""
        import jax
        db, q = dataset
        params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=5)
        index = ivf_flat.build(res, params, db)
        sp = ivf_flat.SearchParams(n_probes=16)
        d_e, i_e = ivf_flat.search(res, sp, index, q, 10)
        d_j, i_j = jax.jit(
            lambda qq: ivf_flat.search(res, sp, index, qq, 10))(
                jnp.asarray(q))
        np.testing.assert_allclose(np.asarray(d_e), np.asarray(d_j),
                                   rtol=1e-4, atol=1e-3)

    def test_inner_product(self, res, dataset):
        db, q = dataset
        dbn = db / np.linalg.norm(db, axis=1, keepdims=True)
        qn = q / np.linalg.norm(q, axis=1, keepdims=True)
        params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=10,
                                      metric=DistanceType.InnerProduct)
        index = ivf_flat.build(res, params, dbn)
        d, i = ivf_flat.search(res, ivf_flat.SearchParams(n_probes=16),
                               index, qn, 5)
        ip = qn @ dbn.T
        ti = np.argsort(-ip, axis=1)[:, :5]
        assert recall(np.asarray(i), ti) > 0.95

    def test_serialize_roundtrip(self, res, dataset):
        db, q = dataset
        params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=5)
        index = ivf_flat.build(res, params, db)
        buf = io.BytesIO()
        ivf_flat.serialize(res, buf, index)
        buf.seek(0)
        index2 = ivf_flat.deserialize(res, buf)
        d1, i1 = ivf_flat.search(res, ivf_flat.SearchParams(n_probes=4),
                                 index, q, 5)
        d2, i2 = ivf_flat.search(res, ivf_flat.SearchParams(n_probes=4),
                                 index2, q, 5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2))

    def test_version_mismatch_fails(self, res, dataset, monkeypatch):
        db, _ = dataset
        params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=2)
        index = ivf_flat.build(res, params, db)
        buf = io.BytesIO()
        # a *well-formed* stream from a future format version must be
        # rejected by the version check, not the CRC
        monkeypatch.setattr(ivf_flat, "_SERIALIZATION_VERSION", 99)
        ivf_flat.serialize(res, buf, index)
        monkeypatch.undo()
        buf.seek(0)
        with pytest.raises(ValueError, match="version"):
            ivf_flat.deserialize(res, buf)

    def test_corrupt_payload_fails(self, res, dataset):
        from raft_tpu.core.serialize import CorruptIndexError
        db, _ = dataset
        params = ivf_flat.IndexParams(n_lists=16, kmeans_n_iters=2)
        index = ivf_flat.build(res, params, db)
        buf = io.BytesIO()
        ivf_flat.serialize(res, buf, index)
        raw = bytearray(buf.getvalue())
        # flip one payload byte: the envelope CRC must catch it
        raw[len(raw) // 2] ^= 0xFF
        with pytest.raises(CorruptIndexError):
            ivf_flat.deserialize(res, io.BytesIO(bytes(raw)))


class TestSuperTileScan:
    """Small-cap lists scan as F-list super-tiles with per-query dedupe
    (round 5: per-group kernel cost is flat in cap, so fragmenting
    pairs over many tiny lists was pure overhead)."""

    def test_supertile_recall_and_no_dups(self, res):
        import numpy as np
        from raft_tpu.neighbors import brute_force, ivf_flat

        rng = np.random.default_rng(17)
        n, dim = 12_000, 32
        X = rng.normal(size=(n, dim)).astype(np.float32)
        Q = rng.normal(size=(64, dim)).astype(np.float32)
        index = ivf_flat.build(
            res, ivf_flat.IndexParams(n_lists=64, kmeans_n_iters=5), X)
        assert index.capacity < 512       # super-tiling engages (F >= 2)
        d, i = ivf_flat.search(res, ivf_flat.SearchParams(n_probes=32),
                               index, Q, 10)
        ii = np.asarray(i)
        for row in ii:
            row = row[row >= 0]
            assert len(set(row.tolist())) == len(row)   # no duplicates
        _, gt = brute_force.knn(res, X, Q, 10)
        gt = np.asarray(gt)
        rec = sum(len(set(a) & set(b)) for a, b in zip(ii, gt)) / gt.size
        assert rec >= 0.9, rec

    def test_supertile_matches_probe_order_scan(self, res):
        import numpy as np
        from raft_tpu.neighbors import ivf_flat

        rng = np.random.default_rng(18)
        n, dim = 8_000, 16
        X = rng.normal(size=(n, dim)).astype(np.float32)
        Q = rng.normal(size=(32, dim)).astype(np.float32)
        index = ivf_flat.build(
            res, ivf_flat.IndexParams(n_lists=64, kmeans_n_iters=5), X)
        d1, i1 = ivf_flat.search(res, ivf_flat.SearchParams(n_probes=64),
                                 index, Q, 10)
        # all lists probed: the result must equal the exhaustive
        # probe-order scan regardless of tiling
        d2, i2 = ivf_flat._search_impl(
            index.centers, index.list_data, index.list_indices,
            jnp.asarray(Q), 10, 64, index.metric)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_supertile_exact_vs_tile_union(self, res):
        """F>1 semantics, checked exactly: a probed list scans its whole
        F-list tile, so the result must equal a brute-force top-k over
        the union of the probed tiles' member rows (covers the probe
        dedupe sentinels, the contiguous reshape, and group building —
        a dropped or corrupted tile cannot hide behind a statistical
        recall bar)."""
        import numpy as np
        from raft_tpu.neighbors import ivf_flat

        rng = np.random.default_rng(19)
        n, dim, k, n_probes = 8_000, 16, 10, 16
        X = rng.normal(size=(n, dim)).astype(np.float32)
        Q = rng.normal(size=(24, dim)).astype(np.float32)
        index = ivf_flat.build(
            res, ivf_flat.IndexParams(n_lists=128, kmeans_n_iters=5), X)
        F, n_eff = ivf_flat.super_tile_factor(index.capacity,
                                              index.n_lists, n_probes)
        assert F >= 2, (index.capacity, F)
        d1, i1 = ivf_flat.search(
            res, ivf_flat.SearchParams(n_probes=n_probes), index, Q, k)
        d1, i1 = np.asarray(d1), np.asarray(i1)
        probes = np.asarray(ivf_flat._select_clusters(
            index.centers, jnp.asarray(Q), n_probes, index.metric))
        ids_by_tile = np.asarray(index.list_indices).reshape(n_eff, -1)
        for q in range(Q.shape[0]):
            tiles = np.unique(probes[q] // F)
            cand = ids_by_tile[tiles].ravel()
            cand = cand[cand >= 0]
            d = np.sum((X[cand] - Q[q]) ** 2, axis=1)
            order = np.argsort(d, kind="stable")[:k]
            np.testing.assert_allclose(d1[q], d[order], rtol=1e-4,
                                       atol=1e-4)
            # a mismatched id is acceptable only as a tie swap — its
            # distance must equal the ground-truth distance at that rank
            gt_ids = cand[order]
            tie_ok = np.abs(d1[q] - d[order]) < 1e-4
            assert ((i1[q] == gt_ids) | tie_ok).all()


class TestCoarseSelection:
    """SearchParams.coarse_recall_target / exact_coarse: the coarse
    probe's approx_max_k knobs (previously hardcoded at 0.95)."""

    def test_params_fields(self):
        sp = ivf_flat.SearchParams(n_probes=8, coarse_recall_target=0.9,
                                   exact_coarse=True)
        assert sp.coarse_recall_target == 0.9
        assert sp.exact_coarse

    def test_exact_coarse_full_probe_recall(self, res, dataset):
        db, q = dataset
        index = ivf_flat.build(
            res, ivf_flat.IndexParams(n_lists=32, kmeans_n_iters=10), db)
        _, truth = naive_knn(db, q, 10)
        sp = ivf_flat.SearchParams(n_probes=32, exact_coarse=True)
        _, i = ivf_flat.search(res, sp, index, q, 10)
        assert recall(np.asarray(i), truth) >= 0.99

    def test_near_full_probe_falls_back_to_exact(self, res, dataset):
        db, q = dataset
        index = ivf_flat.build(
            res, ivf_flat.IndexParams(n_lists=32, kmeans_n_iters=10), db)
        qj = jnp.asarray(q)
        # n_probes=30 >= 32 - 32//8 = 28: approx path auto-falls back to
        # lax.top_k, so it must agree exactly with exact=True
        auto = ivf_flat._select_clusters(index.centers, qj, 30,
                                         index.metric)
        exact = ivf_flat._select_clusters(index.centers, qj, 30,
                                          index.metric, exact=True)
        np.testing.assert_array_equal(np.asarray(auto), np.asarray(exact))

    def test_recall_target_threaded(self, res, dataset):
        db, q = dataset
        index = ivf_flat.build(
            res, ivf_flat.IndexParams(n_lists=32, kmeans_n_iters=10), db)
        _, truth = naive_knn(db, q, 10)
        sp = ivf_flat.SearchParams(n_probes=16, coarse_recall_target=0.99)
        _, i = ivf_flat.search(res, sp, index, q, 10)
        assert recall(np.asarray(i), truth) >= 0.9
