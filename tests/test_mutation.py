"""Mutation-layer tests (ISSUE PR 7, robustness archetype): tombstone
deletes, generation-snapshotted readers, compaction, the CAGRA delete-mask
shim, and mutation x scan-mode parity.

The contract under test: ``delete``/``compact``/``extend`` return a NEW
index generation sharing unchanged arrays with the parent; deleted ids
vanish from every scan formulation (recon / codes / recon8 / fused) via
the existing ``id < 0`` mask with zero kernel changes; ``integrity.verify``
accepts tombstones inside the occupied prefix and rejects them outside it;
the recall canary excludes deleted rows from its ground truth.
"""

import dataclasses
import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import integrity
from raft_tpu.distance.types import DistanceType
from raft_tpu.integrity import IntegrityError
from raft_tpu.integrity import canary as _canary
from raft_tpu.neighbors import cagra, grouped, ivf_flat, ivf_pq
from raft_tpu.neighbors import mutate
from raft_tpu.random import make_blobs


@pytest.fixture(scope="module", autouse=True)
def _drop_compile_caches():
    # this module compiles many one-off mutated-shape variants; release
    # the executables at teardown so later modules in a full-suite run
    # don't inherit the accumulated JIT code mappings
    yield
    jax.clear_caches()


def naive_knn(db, q, k):
    d = ((q[:, None, :] - db[None, :, :]) ** 2).sum(-1)
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, axis=1), idx


def recall(found, truth):
    hits = sum(len(set(f) & set(t)) for f, t in zip(found, truth))
    return hits / truth.size


@pytest.fixture(scope="module")
def res():
    # module-scoped override of conftest's function-scoped fixture so the
    # class-scoped built-index fixtures (building dominates runtime here)
    # can depend on it
    from raft_tpu import DeviceResources
    return DeviceResources(seed=42)


@pytest.fixture(scope="module")
def dataset():
    X, _ = make_blobs(1000, 16, n_clusters=16, cluster_std=1.0, seed=11)
    return np.asarray(X[:950]), np.asarray(X[950:966])


@pytest.fixture(scope="module")
def pq_dataset():
    X, _ = make_blobs(1200, 32, n_clusters=16, cluster_std=1.0, seed=12)
    return np.asarray(X[:1100]), np.asarray(X[1100:1132])


class TestMutateHelpers:
    def test_encode_decode_roundtrip(self):
        ids = jnp.asarray([0, 1, 7, 1 << 20], jnp.int32)
        enc = mutate.encode_tombstones(ids)
        assert bool(jnp.all(enc <= -2))
        np.testing.assert_array_equal(
            np.sort(mutate.decode_tombstones(np.asarray(enc))),
            np.sort(np.asarray(ids)))

    def test_tombstone_hits_only_live_slots(self):
        li = jnp.asarray([[0, 3, -1], [5, -(3 + 2), -1]], jnp.int32)
        out, hit = mutate.tombstone(li, [3, 99])
        # live id 3 is rewritten; the pre-existing tombstone of 3 and the
        # pad slots are untouched; id 99 matches nothing
        np.testing.assert_array_equal(
            np.asarray(out), [[0, -(3 + 2), -1], [5, -(3 + 2), -1]])
        assert int(hit.sum()) == 1

    def test_deleted_ids_subtracts_reinserted(self):
        # id 3 tombstoned in one slot but live in another (the rebalancer's
        # delete -> re-insert pattern): NOT deleted.  id 5 stays deleted.
        li = jnp.asarray([[0, -(3 + 2), -(5 + 2)], [3, 1, -1]], jnp.int32)

        class Stub:
            list_indices = li

        assert mutate.deleted_ids(Stub()) == frozenset({5})

    def test_deleted_ids_prefers_explicit_attr(self):
        class Stub:
            deleted_ids = {4, 9}

        assert mutate.deleted_ids(Stub()) == frozenset({4, 9})

    def test_live_sizes_and_dead_fraction(self):
        li = jnp.asarray([[0, 1, -(2 + 2), -1], [-1, -1, -1, -1]], jnp.int32)

        class Stub:
            list_indices = li

        np.testing.assert_array_equal(np.asarray(mutate.live_sizes(li)),
                                      [2, 0])
        assert mutate.live_count(Stub()) == 2
        assert mutate.dead_fraction(Stub()) == pytest.approx(1 / 3)

    def test_dead_fraction_empty_index(self):
        class Stub:
            list_indices = jnp.full((2, 4), -1, jnp.int32)

        assert mutate.dead_fraction(Stub()) == 0.0

    def test_compaction_order_stable(self):
        li = jnp.asarray([[7, -(1 + 2), 9, -1]], jnp.int32)
        order, live = mutate.compaction_order(li)
        # live rows first, original relative order preserved
        np.testing.assert_array_equal(np.asarray(li[0][order[0]]),
                                      [7, 9, -(1 + 2), -1])
        np.testing.assert_array_equal(np.asarray(live), [2])


class TestTombstoneSerializeRoundTrip:
    """The tombstone decode helpers (``deleted_ids`` / ``live_sizes``)
    must survive serialize -> deserialize: both decode from
    ``list_indices``, which hardened serialization stores verbatim, so a
    checkpointed-and-restored index must report the same delete state
    (the rebalancer's resume path depends on it)."""

    DOOMED = [0, 5, 17, 400]

    def _roundtrip(self, res, module, index):
        buf = io.BytesIO()
        module.serialize(res, buf, index)
        buf.seek(0)
        return module.deserialize(res, buf)

    def _check(self, res, module, index):
        deleted = module.delete(res, index, self.DOOMED)
        back = self._roundtrip(res, module, deleted)
        assert (mutate.deleted_ids(back) == mutate.deleted_ids(deleted)
                == frozenset(self.DOOMED))
        np.testing.assert_array_equal(
            np.asarray(mutate.live_sizes(back.list_indices)),
            np.asarray(mutate.live_sizes(deleted.list_indices)))
        assert mutate.live_count(back) == mutate.live_count(index) - len(
            self.DOOMED)

    def test_flat_roundtrip(self, res, dataset):
        db, _ = dataset
        params = ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=5)
        self._check(res, ivf_flat, ivf_flat.build(res, params, db))

    def test_pq_roundtrip(self, res, pq_dataset):
        db, _ = pq_dataset
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=8,
                                    kmeans_n_iters=5)
        self._check(res, ivf_pq, ivf_pq.build(res, params, db))


class TestFlatMutation:
    @pytest.fixture(scope="class")
    def built(self, res, dataset):
        db, _ = dataset
        params = ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=5)
        return ivf_flat.build(res, params, db)

    def test_delete_excludes_ids(self, res, dataset, built):
        db, q = dataset
        sp = ivf_flat.SearchParams(n_probes=8)
        _, ti = naive_knn(db, q, 10)
        doomed = set(ti[:, 0].tolist())  # every query's true nearest
        idx2 = ivf_flat.delete(res, built, sorted(doomed))
        _, i2 = ivf_flat.search(res, sp, idx2, q, 10)
        found = set(np.asarray(i2).reshape(-1).tolist())
        assert not (found & doomed)
        # survivors still searchable at good recall
        keep = np.asarray([r for r in range(db.shape[0]) if r not in doomed])
        _, ti2 = naive_knn(db[keep], q, 10)
        assert recall(np.asarray(i2), keep[ti2]) > 0.85

    def test_delete_is_a_new_generation(self, res, built):
        idx2 = ivf_flat.delete(res, built, [0])
        assert mutate.generation(idx2) == mutate.generation(built) + 1
        # the parent snapshot is untouched: id 0 still live there
        assert 0 in np.asarray(built.list_indices)
        assert 0 not in np.asarray(idx2.list_indices)[
            np.asarray(idx2.list_indices) >= 0]

    def test_delete_nonexistent_is_noop(self, res, built):
        idx2 = ivf_flat.delete(res, built, [10 ** 7])
        np.testing.assert_array_equal(np.asarray(idx2.list_indices),
                                      np.asarray(built.list_indices))

    def test_compact_reclaims_and_preserves_results(self, res, dataset,
                                                    built):
        db, q = dataset
        sp = ivf_flat.SearchParams(n_probes=8)
        idx2 = ivf_flat.delete(res, built, list(range(0, 200)))
        assert mutate.dead_fraction(idx2) > 0.0
        idx3 = ivf_flat.compact(res, idx2)
        assert mutate.dead_fraction(idx3) == 0.0
        assert mutate.live_count(idx3) == mutate.live_count(idx2)
        assert mutate.generation(idx3) == mutate.generation(idx2) + 1
        _, i2 = ivf_flat.search(res, sp, idx2, q, 10)
        _, i3 = ivf_flat.search(res, sp, idx3, q, 10)
        np.testing.assert_array_equal(np.asarray(i2), np.asarray(i3))
        # post-compact ids are sparse (survivors keep their source ids):
        # verify needs the explicit id span, then passes clean
        integrity.verify(idx3, level="statistical", res=res,
                         n_rows=db.shape[0])

    def test_reinsert_after_delete(self, res, dataset, built):
        db, q = dataset
        sp = ivf_flat.SearchParams(n_probes=8)
        _, ti = naive_knn(db, q, 1)
        rid = int(ti[0, 0])
        idx2 = ivf_flat.delete(res, built, [rid])
        idx3 = ivf_flat.extend(res, idx2, db[rid:rid + 1],
                               np.asarray([rid], np.int64))
        _, i3 = ivf_flat.search(res, sp, idx3, q[:1], 5)
        assert rid in np.asarray(i3)[0].tolist()
        # live copy answers searches -> the id is no longer "deleted"
        assert rid not in mutate.deleted_ids(idx3)
        integrity.verify(idx3, level="statistical", res=res,
                         n_rows=db.shape[0])

    def test_delete_everything_searches_empty(self, res, dataset, built):
        db, q = dataset
        idx2 = ivf_flat.delete(res, built, list(range(db.shape[0])))
        assert mutate.live_count(idx2) == 0
        d, i = ivf_flat.search(res, ivf_flat.SearchParams(n_probes=8),
                               idx2, q, 5)
        np.testing.assert_array_equal(np.asarray(i),
                                      np.full((q.shape[0], 5), -1))


class TestVerifyTombstones:
    @pytest.fixture(scope="class")
    def deleted(self, res, dataset):
        db, _ = dataset
        params = ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=5)
        index = ivf_flat.build(res, params, db)
        return ivf_flat.delete(res, index, list(range(0, 50)))

    def test_verify_accepts_tombstones(self, res, deleted):
        integrity.verify(deleted, level="statistical", res=res)

    def test_tombstone_outside_prefix_fails(self, deleted):
        # a tombstone encoding in the padding region (beyond list_sizes)
        # is corruption, not a delete
        sizes = np.asarray(deleted.list_sizes)
        li = int(np.argmin(sizes))
        assert sizes[li] < deleted.capacity
        bad_li = deleted.list_indices.at[li, deleted.capacity - 1].set(-7)
        bad = dataclasses.replace(deleted, list_indices=bad_li)
        with pytest.raises(IntegrityError) as ei:
            integrity.verify(bad)
        assert ei.value.invariant == "ivf_flat.ids.range" or \
            ei.value.invariant == "ivf_flat.list_sizes.slots"

    def test_live_duplicate_still_fails(self, deleted):
        a = np.asarray(deleted.list_indices)
        # a list holding at least two LIVE slots
        li = int(np.argmax((a >= 0).sum(axis=1)))
        s0, s1 = [int(v) for v in np.flatnonzero(a[li] >= 0)[:2]]
        dup = int(a[li, s1])
        bad_li = deleted.list_indices.at[li, s0].set(dup)
        bad = dataclasses.replace(deleted, list_indices=bad_li)
        with pytest.raises(IntegrityError) as ei:
            integrity.verify(bad)
        assert ei.value.invariant == "ivf_flat.ids.unique"

    def test_live_plus_tombstone_same_id_passes(self, res, deleted):
        # the delete -> re-insert pattern: a live slot sharing its id with
        # a tombstone is legitimate, not a duplicate
        a = np.asarray(deleted.list_indices)
        lives = np.argwhere(a >= 0)
        li, sl = (int(lives[0][0]), int(lives[0][1]))
        live_id = int(a[li, sl])
        tombs = np.argwhere(a <= -2)
        tli, tsl = (int(tombs[0][0]), int(tombs[0][1]))
        patched = deleted.list_indices.at[tli, tsl].set(-(live_id + 2))
        idx = dataclasses.replace(deleted, list_indices=patched)
        integrity.verify(idx, level="structural")

    def test_decoded_tombstone_out_of_range_fails(self, deleted):
        total = int(np.asarray(deleted.list_sizes).sum())
        a = np.asarray(deleted.list_indices)
        tli, tsl = [int(v) for v in np.argwhere(a <= -2)[0]]
        bad_li = deleted.list_indices.at[tli, tsl].set(
            -(total + 100 + 2))
        bad = dataclasses.replace(deleted, list_indices=bad_li)
        with pytest.raises(IntegrityError) as ei:
            integrity.verify(bad)
        assert ei.value.invariant == "ivf_flat.ids.range"


class TestPqMutationParity:
    """Satellite 3: interleaved extend/delete/search must agree across
    every scan formulation, and deleted ids must never surface in ANY
    mode's top-k (fused included — on CPU its Pallas kernels run the
    portable path, same contract)."""

    MODES = ("recon", "codes", "recon8", "fused")

    @pytest.fixture(scope="class")
    def built(self, res, pq_dataset):
        db, _ = pq_dataset
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=8,
                                    kmeans_n_iters=5)
        return ivf_pq.build(res, params, db)

    def _search_all_modes(self, res, index, q, k, kt=0):
        out = {}
        for mode in self.MODES:
            sp = ivf_pq.SearchParams(n_probes=16, scan_mode=mode,
                                     per_probe_topk=kt)
            _, i = ivf_pq.search(res, sp, index, q, k)
            out[mode] = np.asarray(i)
        return out

    def test_interleaved_mutations_agree_across_modes(self, res,
                                                      pq_dataset, built):
        db, q = pq_dataset
        rng = np.random.default_rng(20260805)
        index, n = built, db.shape[0]
        deleted = set()
        for rnd in range(3):
            doom = rng.choice([r for r in range(n) if r not in deleted],
                              size=40, replace=False)
            index = ivf_pq.delete(res, index, np.sort(doom))
            deleted.update(int(v) for v in doom)
            extra = rng.normal(size=(16, db.shape[1])).astype(np.float32)
            index = ivf_pq.extend(res, index, extra,
                                  np.arange(n, n + 16, dtype=np.int64))
            n += 16
            # matched kt across modes, both the exact-merge default and a
            # narrowed per-probe keep-set: deleted ids surface in NEITHER
            for kt in (0, 4):
                by_mode = self._search_all_modes(res, index, q, 10, kt=kt)
                for mode, ids in by_mode.items():
                    hit = set(ids.reshape(-1).tolist()) & deleted
                    assert not hit, (rnd, kt, mode, hit)
                if kt:
                    continue
                # at the exact merge the quantized modes keep essentially
                # the recon reference's candidates (int8/LUT noise only)
                for mode in ("codes", "recon8", "fused"):
                    ov = np.mean([len(set(a) & set(b)) / 10 for a, b in
                                  zip(by_mode[mode], by_mode["recon"])])
                    assert ov > 0.9, (rnd, mode, ov)
        # 3 x (delete + extend) on top of wherever build started
        assert mutate.generation(index) == mutate.generation(built) + 6

    def test_deleted_never_in_topk_property(self, res, pq_dataset, built):
        db, q = pq_dataset
        _, ti = naive_knn(db, q, 5)
        doomed = sorted(set(ti.reshape(-1).tolist()))  # the whole true top-5
        index = ivf_pq.delete(res, built, doomed)
        by_mode = self._search_all_modes(res, index, q, 10)
        for mode, ids in by_mode.items():
            assert not (set(ids.reshape(-1).tolist()) & set(doomed)), mode

    def test_compact_preserves_mode_results(self, res, pq_dataset, built):
        db, q = pq_dataset
        index = ivf_pq.delete(res, built, list(range(0, 300)))
        compacted = ivf_pq.compact(res, index)
        assert mutate.dead_fraction(compacted) == 0.0
        before = self._search_all_modes(res, index, q, 10)
        after = self._search_all_modes(res, compacted, q, 10)
        for mode in self.MODES:
            ov = np.mean([len(set(a) & set(b)) / 10 for a, b in
                          zip(before[mode], after[mode])])
            assert ov > 0.9, mode
        integrity.verify(compacted, level="statistical", res=res,
                         n_rows=db.shape[0])

    def test_all_deleted_returns_sentinels_every_mode(self, res,
                                                      pq_dataset, built):
        db, q = pq_dataset
        index = ivf_pq.delete(res, built, list(range(db.shape[0])))
        for mode, ids in self._search_all_modes(res, index, q, 5).items():
            np.testing.assert_array_equal(
                ids, np.full((q.shape[0], 5), -1), err_msg=mode)


class TestGroupedDegenerate:
    """Satellite 2: the grouped machinery must tolerate lists emptied by
    delete/compaction — empty pair groups, zero probes, zero capacity."""

    def test_probe_overlap_order_zero_probes(self):
        probes = jnp.zeros((5, 0), jnp.int32)
        np.testing.assert_array_equal(
            np.asarray(grouped.probe_overlap_order(probes, 8)),
            np.arange(5))

    def test_block_size_zero_groups(self):
        assert grouped.block_size(0, 1024) >= 1
        assert grouped.block_size(0, 0) >= 1

    def test_scan_and_scatter_zero_groups(self):
        gl = jnp.zeros((0,), jnp.int32)
        sp = jnp.zeros((0, grouped.GROUP), jnp.int32)
        d, i = grouped.scan_and_scatter(gl, sp, 8, 64, 5, True,
                                        grouped.block_size(0, 1024),
                                        None, None)
        assert d.shape == (8, 5) and i.shape == (8, 5)
        assert bool(jnp.all(jnp.isinf(d))) and bool(jnp.all(i == -1))

    def test_scan_and_scatter_zero_cap(self):
        gl = jnp.zeros((4,), jnp.int32)
        sp = jnp.zeros((4, grouped.GROUP), jnp.int32)
        d, i = grouped.scan_and_scatter(gl, sp, 8, 0, 5, False, 4,
                                        None, None, kt=3)
        # cap == 0: kt falls back to the requested kt, ids all sentinel
        assert d.shape == (8, 3) and i.shape == (8, 3)
        assert bool(jnp.all(jnp.isneginf(d))) and bool(jnp.all(i == -1))

    def test_finalize_topk_clamps_encoded_ids(self):
        # k exceeding the candidate count must never leak a tombstone
        # encoding (<= -2) into public results
        from raft_tpu.matrix.select_k import select_k
        outd = jnp.asarray([[0.5, jnp.inf, jnp.inf]], jnp.float32)
        outi = jnp.asarray([[3, -7, -9]], jnp.int32)
        d, i = grouped.finalize_topk(outd, outi, 1, 3, True, False,
                                     select_k)
        assert bool(jnp.all(i >= -1)), np.asarray(i)


class TestCagraShim:
    @pytest.fixture(scope="class")
    def built(self, res, dataset):
        # The delete shim only masks at search time, so an exact brute-force
        # kNN graph stands in for the (much slower) cagra.build pipeline.
        db, _ = dataset
        _, nbrs = naive_knn(db, db, 17)
        graph = jnp.asarray(nbrs[:, 1:].astype(np.int32))
        return cagra.Index(dataset=jnp.asarray(db), graph=graph), db

    def test_delete_masks_results(self, res, dataset, built):
        index, db = built
        _, q = dataset
        _, ti = naive_knn(db, q, 1)
        doomed = sorted(set(ti[:, 0].tolist()))
        idx2 = cagra.delete(res, index, doomed)
        assert mutate.deleted_ids(idx2) == frozenset(doomed)
        assert mutate.generation(idx2) == mutate.generation(index) + 1
        sp = cagra.SearchParams(itopk_size=32)
        _, i2 = cagra.search(res, sp, idx2, q, 10)
        assert not (set(np.asarray(i2).reshape(-1).tolist()) & set(doomed))
        # parent snapshot still serves the deleted rows
        _, i1 = cagra.search(res, sp, index, q, 1)
        assert set(np.asarray(i1).reshape(-1).tolist()) & set(doomed)

    def test_delete_accumulates(self, res, built):
        index, _ = built
        idx2 = cagra.delete(res, index, [1, 2])
        idx3 = cagra.delete(res, idx2, [3])
        assert mutate.deleted_ids(idx3) == frozenset({1, 2, 3})

    def test_results_stay_sorted_after_mask(self, res, dataset, built):
        index, db = built
        _, q = dataset
        idx2 = cagra.delete(res, index, list(range(0, 100)))
        d2, _ = cagra.search(res, cagra.SearchParams(itopk_size=32),
                             idx2, q, 10)
        d2 = np.asarray(d2)
        # masked slots carry +inf; cap them so inf-inf row tails don't
        # turn the monotonicity diff into NaN
        capped = np.where(np.isfinite(d2), d2, np.finfo(np.float32).max)
        assert np.all(np.diff(capped, axis=1) >= -1e-6)


class TestCanaryExclusion:
    @pytest.fixture(scope="class")
    def canaried(self, res, dataset):
        db, _ = dataset
        params = ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=5,
                                      canary_queries=16, canary_k=5,
                                      canary_floor=0.3)
        return ivf_flat.build(res, params, db)

    def test_health_check_survives_deleting_ground_truth(self, res,
                                                         canaried):
        # delete rows that ARE canary ground truth: recall would crater if
        # measure() kept counting them; the exclusion keeps it honest
        gt_ids = sorted(set(
            int(v) for v in np.asarray(canaried.canaries.gt_ids)
            .reshape(-1) if int(v) >= 0))
        doomed = gt_ids[:len(gt_ids) // 2]
        idx2 = ivf_flat.delete(res, canaried, doomed)
        report = _canary.health_check(res, idx2, raise_on_fail=True)
        assert report.ok

    def test_measure_all_ground_truth_deleted(self, res, canaried):
        gt_ids = sorted(set(
            int(v) for v in np.asarray(canaried.canaries.gt_ids)
            .reshape(-1) if int(v) >= 0))
        idx2 = ivf_flat.delete(res, canaried, gt_ids)
        # zero live ground truth -> vacuous 1.0, not a 0/0 crash
        assert _canary.measure(res, idx2, idx2.canaries) == 1.0


@pytest.mark.slow
class TestDistributedDelete:
    @pytest.fixture
    def session(self, mesh8):
        from raft_tpu.comms import CommsSession
        s = CommsSession(mesh=mesh8, axis_name="data").init()
        yield s
        s.destroy()

    @pytest.fixture
    def handle(self, session):
        return session.worker_handle(seed=0)

    def test_delete_excludes_global_ids(self, handle):
        from raft_tpu.distributed import ann
        rng = np.random.default_rng(3)
        db = rng.normal(size=(1024, 16)).astype(np.float32)
        q = rng.normal(size=(16, 16)).astype(np.float32)
        params = ivf_pq.IndexParams(n_lists=4, pq_dim=4, kmeans_n_iters=3)
        index = ann.build(handle, params, db)
        sp = ivf_pq.SearchParams(n_probes=4)
        _, i1 = ann.search(handle, sp, index, q, 10)
        doomed = sorted(set(np.asarray(i1)[:, 0].tolist()) - {-1})
        assert doomed
        idx2 = ann.delete(handle, index, doomed)
        assert mutate.generation(idx2) == mutate.generation(index) + 1
        _, i2 = ann.search(handle, sp, idx2, q, 10)
        assert not (set(np.asarray(i2).reshape(-1).tolist()) & set(doomed))
        # parent snapshot untouched
        _, i1b = ann.search(handle, sp, index, q, 10)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i1b))


class TestUpsert:
    """Satellite (PR 8): ``upsert`` = delete + extend under one id with
    ONE generation bump, so a churn loop advances generation-keyed
    caches once per batch instead of twice."""

    @pytest.fixture(scope="class")
    def flat_built(self, res, dataset):
        db, _ = dataset
        return ivf_flat.build(res, ivf_flat.IndexParams(
            n_lists=8, kmeans_n_iters=5), db)

    @pytest.fixture(scope="class")
    def pq_built(self, res, pq_dataset):
        db, _ = pq_dataset
        return ivf_pq.build(res, ivf_pq.IndexParams(
            n_lists=16, pq_dim=8, kmeans_n_iters=5), db)

    def test_flat_upsert_replaces_under_same_id(self, res, dataset,
                                                flat_built):
        db, _ = dataset
        rng = np.random.default_rng(81)
        ids = np.asarray([3, 40, 77], np.int32)
        vecs = rng.normal(size=(3, db.shape[1])).astype(np.float32) * 0.01
        out = ivf_flat.upsert(res, flat_built, ids, vecs)
        sp = ivf_flat.SearchParams(n_probes=8)
        _, i = ivf_flat.search(res, sp, out, vecs, 1)
        np.testing.assert_array_equal(np.sort(np.asarray(i).ravel()), ids)
        # the old rows under those ids no longer resolve: searching the
        # ORIGINAL vectors must not return the upserted ids at rank 0
        # from their old location (each id now lives at the new vector)
        d2, i2 = ivf_flat.search(res, sp, out, db[ids], 1)
        old_self_dist = np.asarray(d2)[np.asarray(i2).ravel() == ids]
        assert not np.any(np.isclose(old_self_dist, 0.0))

    def test_flat_one_generation_bump(self, res, dataset, flat_built):
        db, _ = dataset
        out = ivf_flat.upsert(res, flat_built, np.asarray([5], np.int32),
                              db[5:6] + 0.5)
        assert mutate.generation(out) == mutate.generation(flat_built) + 1

    def test_pq_upsert_replaces_under_same_id(self, res, pq_dataset,
                                              pq_built):
        db, _ = pq_dataset
        rng = np.random.default_rng(82)
        ids = np.asarray([10, 200, 999], np.int32)
        vecs = rng.normal(size=(3, db.shape[1])).astype(np.float32)
        out = ivf_pq.upsert(res, pq_built, ids, vecs)
        assert mutate.generation(out) == mutate.generation(pq_built) + 1
        sp = ivf_pq.SearchParams(n_probes=16)
        _, i = ivf_pq.search(res, sp, out, vecs, 1)
        np.testing.assert_array_equal(np.sort(np.asarray(i).ravel()), ids)
        # each id is live exactly once (the delete half removed the old
        # copy before the extend half appended the new one)
        li = np.asarray(out.list_indices)
        for v in ids:
            assert int((li == v).sum()) == 1

    def test_pq_upsert_inserts_fresh_ids(self, res, pq_dataset, pq_built):
        db, _ = pq_dataset
        fresh = np.asarray([db.shape[0] + 7], np.int32)  # not in index
        out = ivf_pq.upsert(res, pq_built, fresh, db[:1] * 1.001)
        li = np.asarray(out.list_indices)
        assert int((li == fresh[0]).sum()) == 1

    def test_pq_churn_loop(self, res, pq_dataset, pq_built):
        """Sustained upsert churn: repeatedly rewrite a rotating window
        of ids; generation advances by exactly one per batch, every id
        stays live exactly once, and recall against the evolving ground
        truth holds."""
        db, q = pq_dataset
        rng = np.random.default_rng(83)
        cur = np.array(db, copy=True)
        index = pq_built
        n = db.shape[0]
        sp = ivf_pq.SearchParams(n_probes=16)
        _, f0 = ivf_pq.search(res, sp, index, q, 10)
        _, t0 = naive_knn(db, np.asarray(q), 10)
        base_recall = recall(np.asarray(f0), t0)
        for rnd in range(4):
            ids = rng.choice(n, size=64, replace=False).astype(np.int32)
            # perturbed copies of other dataset rows: stays inside the
            # codebook's support so PQ recall is meaningful
            src = rng.choice(n, size=64).astype(np.int32)
            vecs = (db[src] + 0.05 * rng.normal(
                size=(64, db.shape[1]))).astype(np.float32)
            g = mutate.generation(index)
            index = ivf_pq.upsert(res, index, ids, vecs)
            assert mutate.generation(index) == g + 1
            cur[ids] = vecs
        li = np.asarray(index.list_indices)
        live = li[li >= 0]
        assert live.size == n and np.unique(live).size == n
        _, found = ivf_pq.search(res, sp, index, q, 10)
        _, truth = naive_knn(cur, np.asarray(q), 10)
        # churn must not degrade recall materially below the index's own
        # pre-churn recall (PQ quantization bounds both the same way)
        assert recall(np.asarray(found), truth) > base_recall - 0.08
