"""Filtered & multi-tenant search (round 20).

The filtered-parity contract is the backbone: a filtered search at full
probe must be BIT-IDENTICAL to taking the unfiltered result at a huge k
and dropping inadmissible rows post-hoc — on every scan formulation
(lut / recon / codes / recon8 / fused), on brute force, on ivf_flat,
and across the routed distributed dispatch.  Everything else (tenancy,
hybrid dense+sparse, serving integration, zero-recompile) layers on
that seam.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu import serving
from raft_tpu import integrity
from raft_tpu import observability as obs
from raft_tpu.core.error import LogicError
from raft_tpu.filters import (SampleFilter, TenantFilter,
                              candidates_to_filter, query_filter_words)
from raft_tpu.filters import bitset as fb
from raft_tpu.integrity import canary
from raft_tpu.integrity.errors import IntegrityError
from raft_tpu.neighbors import brute_force, grouped, ivf_flat, ivf_pq

N, DIM, NQ, K = 2000, 32, 8, 10
FULL = ivf_pq.SearchParams(n_probes=16, exact_coarse=True)


@pytest.fixture(scope="module")
def dataset():
    rng = np.random.default_rng(0)
    db = rng.standard_normal((N, DIM)).astype(np.float32)
    q = rng.standard_normal((NQ, DIM)).astype(np.float32)
    mask = rng.random((NQ, N)) < 0.5
    return db, q, mask


@pytest.fixture(scope="module")
def mres():
    from raft_tpu import DeviceResources
    return DeviceResources(seed=42)


@pytest.fixture(scope="module")
def pq_index(mres, dataset):
    db, _, _ = dataset
    return ivf_pq.build(
        mres, ivf_pq.IndexParams(n_lists=16, pq_dim=8, kmeans_n_iters=4),
        jnp.asarray(db))


def posthoc_reference(d_u, i_u, mask, k):
    """Drop inadmissible rows from a big unfiltered result, keep k."""
    d_u, i_u = np.asarray(d_u), np.asarray(i_u)
    nq = d_u.shape[0]
    ref_d = np.full((nq, k), np.inf, np.float32)
    ref_i = np.full((nq, k), -1, np.int32)
    for qi in range(nq):
        keep = [(d_u[qi, j], i_u[qi, j]) for j in range(d_u.shape[1])
                if i_u[qi, j] >= 0 and mask[qi, i_u[qi, j]]][:k]
        for j, (dv, iv) in enumerate(keep):
            ref_d[qi, j], ref_i[qi, j] = dv, iv
    return ref_d, ref_i


# ---------------------------------------------------------------------------
# the bitset itself


class TestBitset:
    def test_pack_unpack_roundtrip(self):
        rng = np.random.default_rng(3)
        mask = rng.random((4, 70)) < 0.5
        words = fb.pack_mask(jnp.asarray(mask))
        assert words.shape == (4, 3)
        back = np.asarray(fb.unpack_words(words, 70))
        np.testing.assert_array_equal(back != 0, mask)

    def test_from_ids_and_counts(self):
        f = SampleFilter.from_ids([0, 33, 64], 70)
        assert f.n_words == 3
        counts = f.admitted_counts()
        assert counts.tolist() == [3]
        m = np.asarray(fb.unpack_words(f.words, 70))[0] != 0
        assert sorted(np.nonzero(m)[0].tolist()) == [0, 33, 64]

    def test_all_rows_admits_tail_padding_only_to_coverage(self):
        f = SampleFilter.all_rows(40)
        assert f.admitted_counts().tolist() == [40]

    def test_intersect(self):
        a = SampleFilter.from_ids([1, 2, 3], 64)
        b = SampleFilter.from_ids([2, 3, 4], 64)
        assert a.intersect(b).admitted_counts().tolist() == [2]

    def test_query_bits_rejects_out_of_range(self):
        f = SampleFilter.from_ids([0, 1], 64)
        qids = jnp.zeros((1,), jnp.int32)
        ids = jnp.asarray([[0, -1, 63, 10_000]], jnp.int32)
        bits = np.asarray(fb.query_bits(f.words, qids, ids))
        assert bits[0].tolist() == [1, 0, 0, 0]

    def test_query_filter_words_nq_mismatch_raises(self):
        f = SampleFilter.from_mask(np.ones((2, 64), bool))
        with pytest.raises(LogicError):
            query_filter_words(f, 5, "t")   # nq=2 batch=5: not broadcastable

    def test_mask_and_filter_normalize_identically(self):
        rng = np.random.default_rng(4)
        mask = rng.random((3, 50)) < 0.5
        w1 = query_filter_words(jnp.asarray(mask), 3, "t")
        w2 = query_filter_words(SampleFilter.from_mask(jnp.asarray(mask)),
                                3, "t")
        np.testing.assert_array_equal(np.asarray(w1), np.asarray(w2))


# ---------------------------------------------------------------------------
# filtered parity on every ivf_pq scan formulation


class TestFilteredParity:
    @pytest.mark.parametrize(
        "mode", ["lut", "recon", "codes", "recon8", "fused"])
    def test_scan_mode_bit_identical_to_posthoc(self, mres, pq_index,
                                                dataset, mode):
        db, q, mask = dataset
        p = ivf_pq.SearchParams(n_probes=16, exact_coarse=True,
                                scan_mode=mode)
        d_u, i_u = ivf_pq.search(mres, p, pq_index, jnp.asarray(q), 512)
        ref_d, ref_i = posthoc_reference(d_u, i_u, mask, K)
        d_f, i_f = ivf_pq.search(mres, p, pq_index, jnp.asarray(q), K,
                                 filter=SampleFilter.from_mask(mask))
        np.testing.assert_array_equal(np.asarray(i_f), ref_i)
        np.testing.assert_array_equal(np.asarray(d_f), ref_d)

    @pytest.mark.parametrize("selectivity", [0.001, 0.5, 1.0])
    def test_selectivity_sweep(self, mres, pq_index, dataset, selectivity):
        _, q, _ = dataset
        rng = np.random.default_rng(int(selectivity * 1000))
        mask = rng.random((NQ, N)) < selectivity
        # k = N: at 0.001 selectivity the handful of admitted rows sit
        # far outside any truncated unfiltered prefix
        p = ivf_pq.SearchParams(n_probes=16, exact_coarse=True,
                                scan_mode="lut")
        d_u, i_u = ivf_pq.search(mres, p, pq_index, jnp.asarray(q), N)
        ref_d, ref_i = posthoc_reference(d_u, i_u, mask, K)
        d_f, i_f = ivf_pq.search(mres, p, pq_index, jnp.asarray(q), K,
                                 filter=SampleFilter.from_mask(mask))
        np.testing.assert_array_equal(np.asarray(i_f), ref_i)
        np.testing.assert_array_equal(np.asarray(d_f), ref_d)

    def test_all_rows_filtered_yields_sentinels(self, mres, pq_index,
                                                dataset):
        _, q, _ = dataset
        empty = SampleFilter.from_mask(np.zeros((NQ, N), bool))
        d, i = ivf_pq.search(mres, FULL, pq_index, jnp.asarray(q), K,
                             filter=empty)
        assert (np.asarray(i) == -1).all()
        assert np.isinf(np.asarray(d)).all()

    def test_single_row_filter_broadcasts(self, mres, pq_index, dataset):
        _, q, mask = dataset
        one = np.broadcast_to(mask[:1], (NQ, N))
        d_b, i_b = ivf_pq.search(mres, FULL, pq_index, jnp.asarray(q), K,
                                 filter=SampleFilter.from_mask(mask[:1]))
        d_f, i_f = ivf_pq.search(mres, FULL, pq_index, jnp.asarray(q), K,
                                 filter=SampleFilter.from_mask(one))
        np.testing.assert_array_equal(np.asarray(i_b), np.asarray(i_f))

    def test_filter_composes_with_tombstones(self, mres, pq_index,
                                             dataset):
        _, q, mask = dataset
        # delete half the admitted world; neither deleted nor filtered
        # rows may surface, and parity holds on the surviving set
        doomed = np.nonzero(mask[0])[0][:200].tolist()
        mutated = ivf_pq.delete(mres, pq_index, doomed)
        d_u, i_u = ivf_pq.search(mres, FULL, mutated, jnp.asarray(q), 512)
        ref_d, ref_i = posthoc_reference(d_u, i_u, mask, K)
        d_f, i_f = ivf_pq.search(mres, FULL, mutated, jnp.asarray(q), K,
                                 filter=SampleFilter.from_mask(mask))
        np.testing.assert_array_equal(np.asarray(i_f), ref_i)
        np.testing.assert_array_equal(np.asarray(d_f), ref_d)
        live = np.asarray(i_f)
        assert not np.isin(live[live >= 0], doomed).any()


# ---------------------------------------------------------------------------
# the Pallas kernels (interpret mode) against their XLA twins


class TestPallasAdmissionParity:
    def test_grouped_kernels_match_xla_twin(self, mres):
        from raft_tpu.neighbors.ivf_pq import (
            _search_impl_codes_grouped, _search_impl_fused_codes_grouped,
            _search_impl_fused_recon_grouped, _search_impl_recon_grouped,
            _select_clusters, _with_code_lanes)

        rng = np.random.default_rng(1)
        n, nq, k = 1024, 8, 8
        data = rng.standard_normal((n, DIM)).astype(np.float32)
        q = jnp.asarray(rng.standard_normal((nq, DIM)).astype(np.float32))
        idx = _with_code_lanes(ivf_pq.build(
            mres, ivf_pq.IndexParams(n_lists=8, pq_dim=8), data))
        probes = _select_clusters(idx.centers, idx.rotation, q, 8,
                                  idx.metric, exact=True)
        ng, _ = grouped.group_capacity(nq, 8, idx.n_lists)
        mask = rng.random((nq, n)) < 0.4
        fw = query_filter_words(SampleFilter.from_mask(mask), nq, "t")

        d_ref, i_ref = _search_impl_recon_grouped(
            idx.centers, idx.list_recon, idx.list_recon_sq,
            idx.list_indices, idx.rotation, q, probes, k, idx.metric, ng,
            64, use_pallas=False, filter_words=fw)
        d_ref, i_ref = np.asarray(d_ref), np.asarray(i_ref)

        d_p, i_p = _search_impl_recon_grouped(
            idx.centers, idx.list_recon, idx.list_recon_sq,
            idx.list_indices, idx.rotation, q, probes, k, idx.metric, ng,
            64, use_pallas=True, pallas_interpret=True, filter_words=fw)
        np.testing.assert_array_equal(np.asarray(i_p), i_ref)

        d_f, i_f = _search_impl_fused_recon_grouped(
            idx.centers, idx.list_recon, idx.list_recon_sq,
            idx.list_indices, idx.rotation, q, probes, k, k, idx.metric,
            ng, merge_window=2, pallas_interpret=True, filter_words=fw)
        np.testing.assert_array_equal(np.asarray(i_f), i_ref)
        np.testing.assert_allclose(np.asarray(d_f), d_ref,
                                   rtol=1e-5, atol=1e-5)

        d_c, i_c = _search_impl_codes_grouped(
            idx.centers, idx.codebooks, idx.list_code_lanes,
            idx.list_code_rsq, idx.list_indices, idx.rotation, q, probes,
            k, k, idx.metric, ng, idx.pq_bits, pallas_interpret=True,
            filter_words=fw)
        np.testing.assert_array_equal(np.asarray(i_c), i_ref)

        d_fc, i_fc = _search_impl_fused_codes_grouped(
            idx.centers, idx.codebooks, idx.list_code_lanes,
            idx.list_code_rsq, idx.list_indices, idx.rotation, q, probes,
            k, k, idx.metric, ng, idx.pq_bits, merge_window=2,
            pallas_interpret=True, filter_words=fw)
        np.testing.assert_array_equal(np.asarray(i_fc), i_ref)


# ---------------------------------------------------------------------------
# brute force / ivf_flat / cagra


class TestOtherIndexKinds:
    def test_brute_force_matches_numpy_reference(self, mres, dataset):
        db, q, mask = dataset
        d, i = brute_force.knn(mres, jnp.asarray(db), jnp.asarray(q), K,
                               filter=SampleFilter.from_mask(mask))
        d, i = np.asarray(d), np.asarray(i)
        dist = ((q[:, None, :] - db[None, :, :]) ** 2).sum(-1)
        ref_d = np.where(mask, dist, np.inf)
        order = np.argsort(ref_d, axis=1, kind="stable")[:, :K]
        rd = np.take_along_axis(ref_d, order, axis=1)
        ri = np.where(np.isinf(rd), -1, order)
        np.testing.assert_array_equal(i, ri)
        np.testing.assert_allclose(np.where(np.isinf(d), 0, d),
                                   np.where(np.isinf(rd), 0, rd),
                                   atol=1e-3)

    def test_brute_force_filter_addresses_global_ids(self, mres, dataset):
        db, q, mask = dataset
        off = 6400   # word-aligned shard offset
        pad = jnp.zeros((NQ, off // 32), jnp.int32)
        base = SampleFilter.from_mask(mask)
        shifted = SampleFilter.from_words(
            jnp.concatenate([pad, base.words], axis=1), off + N)
        d0, i0 = brute_force.knn(mres, jnp.asarray(db), jnp.asarray(q), K,
                                 filter=base)
        d1, i1 = brute_force.knn(mres, jnp.asarray(db), jnp.asarray(q), K,
                                 filter=shifted, global_id_offset=off)
        i0, i1 = np.asarray(i0), np.asarray(i1)
        np.testing.assert_array_equal(np.where(i1 >= 0, i1 - off, -1), i0)

    def test_ivf_flat_full_probe_parity(self, mres, dataset):
        db, q, mask = dataset
        idx = ivf_flat.build(
            mres, ivf_flat.IndexParams(n_lists=16, metric=0),
            jnp.asarray(db))
        sp = ivf_flat.SearchParams(n_probes=16)
        d_u, i_u = ivf_flat.search(mres, sp, idx, jnp.asarray(q), 512)
        ref_d, ref_i = posthoc_reference(d_u, i_u, mask, K)
        d_f, i_f = ivf_flat.search(mres, sp, idx, jnp.asarray(q), K,
                                   filter=SampleFilter.from_mask(mask))
        np.testing.assert_array_equal(np.asarray(i_f), ref_i)
        np.testing.assert_allclose(
            np.where(np.isinf(np.asarray(d_f)), 0, np.asarray(d_f)),
            np.where(np.isinf(ref_d), 0, ref_d), atol=1e-3)

    def test_cagra_admits_only_filtered(self, mres, dataset):
        from raft_tpu.neighbors import cagra
        db, q, mask = dataset
        # admission semantics don't depend on how the graph was built —
        # assemble the Index from an exact numpy kNN graph instead of
        # paying the full cagra.build (the build has its own tests)
        n_sub, deg = 512, 16
        sub, msub = np.asarray(db)[:n_sub], mask[:, :n_sub]
        dist = ((sub[:, None, :] - sub[None, :, :]) ** 2).sum(-1)
        np.fill_diagonal(dist, np.inf)
        graph = np.argsort(dist, axis=1, kind="stable")[:, :deg]
        idx = cagra.Index(dataset=jnp.asarray(sub),
                          graph=jnp.asarray(graph, jnp.int32))
        sp = cagra.SearchParams(itopk_size=64, search_width=4)
        d, i = cagra.search(mres, sp, idx, jnp.asarray(q), K,
                            filter=SampleFilter.from_mask(msub))
        i = np.asarray(i)
        assert all(msub[qi, ii] for qi in range(NQ)
                   for ii in i[qi] if ii >= 0)
        # all-rows filter is the identity
        d1, i1 = cagra.search(mres, sp, idx, jnp.asarray(q), K)
        d2, i2 = cagra.search(mres, sp, idx, jnp.asarray(q), K,
                              filter=SampleFilter.all_rows(n_sub))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        # total rejection folds to sentinels
        d3, i3 = cagra.search(mres, sp, idx, jnp.asarray(q), K,
                              filter=SampleFilter.from_mask(
                                  np.zeros((NQ, n_sub), bool)))
        assert (np.asarray(i3) == -1).all()


# ---------------------------------------------------------------------------
# hybrid dense+sparse


class TestHybrid:
    def test_candidates_to_filter_skips_padding(self):
        f = candidates_to_filter(np.asarray([[3, -1, 5], [0, 1, -1]]), 64)
        assert f.admitted_counts().tolist() == [2, 2]
        m = np.asarray(fb.unpack_words(f.words, 64)) != 0
        assert sorted(np.nonzero(m[0])[0].tolist()) == [3, 5]
        assert sorted(np.nonzero(m[1])[0].tolist()) == [0, 1]

    def test_hybrid_search_restricts_to_sparse_candidates(self, mres,
                                                          pq_index,
                                                          dataset):
        from raft_tpu import sparse as sp_mod
        from raft_tpu.filters import hybrid
        db, q, _ = dataset
        # lexical side: a random nonnegative "term" view of the corpus
        rng = np.random.default_rng(7)
        lex_db = np.maximum(db, 0) * (rng.random((N, DIM)) < 0.3)
        lex_q = np.maximum(q, 0)
        cdb = sp_mod.dense_to_csr(jnp.asarray(lex_db.astype(np.float32)))
        cq = sp_mod.dense_to_csr(jnp.asarray(lex_q.astype(np.float32)))
        k_sparse = 64
        d, i = hybrid.search(mres, FULL, pq_index, jnp.asarray(q), K,
                             sparse_queries=cq, sparse_database=cdb,
                             k_sparse=k_sparse)
        from raft_tpu.distance.types import DistanceType
        _, cand = sp_mod.brute_force_knn_sparse(
            cq, cdb, k_sparse, metric=DistanceType.InnerProduct)
        cand = np.asarray(cand)
        i = np.asarray(i)
        for qi in range(NQ):
            allowed = set(cand[qi][cand[qi] >= 0].tolist())
            assert set(i[qi][i[qi] >= 0].tolist()) <= allowed
        # and parity: hybrid == ivf_pq.search with the candidate filter
        filt = candidates_to_filter(cand, N)
        d2, i2 = ivf_pq.search(mres, FULL, pq_index, jnp.asarray(q), K,
                               filter=filt)
        np.testing.assert_array_equal(i, np.asarray(i2))


# ---------------------------------------------------------------------------
# tenancy: TenantFilter, namespace verification, filtered canaries


class TestTenancy:
    def test_tenant_filter_invariants(self):
        t = TenantFilter(ranges={"a": (0, 100), "b": (100, 256)},
                         n_rows=256)
        assert t.owner_of(0) == "a" and t.owner_of(255) == "b"
        assert t.owner_of(256) is None
        wa = t.words_for("a")
        assert (np.asarray(fb.unpack_words(jnp.asarray(wa)[None], 256))
                [0, :100] != 0).all()
        f = t.filter_for("a", 3)
        assert f.nq == 3 and f.n_rows == 256
        with pytest.raises(LogicError):
            TenantFilter(ranges={"a": (0, 150), "b": (100, 256)},
                         n_rows=256)
        with pytest.raises(LogicError):
            t.words_for("nope")

    def test_verify_namespaces(self, mres, pq_index):
        good = TenantFilter(ranges={"a": (0, 1000), "b": (1000, N)},
                            n_rows=N)
        integrity.verify(pq_index, namespaces=good)
        # a namespace map that strands live ids fails coverage
        short = TenantFilter(ranges={"a": (0, 1000)}, n_rows=N)
        with pytest.raises(IntegrityError) as e:
            integrity.verify(pq_index, namespaces=short)
        assert e.value.invariant == "namespace.coverage"

    def test_canary_filtered_variant(self, mres, pq_index, dataset):
        db, _, _ = dataset
        cs = canary.make(mres, jnp.asarray(db), metric=0)
        tenants = TenantFilter(ranges={"a": (0, 1000), "b": (1000, N)},
                               n_rows=N)
        r = canary.measure(mres, pq_index, cs,
                           filter=tenants.filter_for("a", 1))
        assert 0.0 <= r <= 1.0
        # an all-rejecting filter leaves nothing to find: recall 1.0
        nothing = SampleFilter.from_words(
            jnp.zeros((1, fb.n_words_for(N)), jnp.int32), N)
        assert canary.measure(mres, pq_index, cs, filter=nothing) == 1.0


# ---------------------------------------------------------------------------
# serving: executor parity, tenancy end-to-end, zero recompiles


class TestServing:
    def test_executor_filtered_parity_and_default(self, mres, pq_index,
                                                  dataset):
        _, q, mask = dataset
        ex = serving.Executor(mres, "ivf_pq", pq_index, ks=(K,),
                              max_batch=NQ, search_params=FULL,
                              warm="jit", filter_rows=N)
        fw = query_filter_words(SampleFilter.from_mask(mask), NQ, "t")
        d1, i1 = ex.search_bucket(jnp.asarray(q), NQ, K, filter_words=fw)
        d2, i2 = ivf_pq.search(mres, FULL, pq_index, jnp.asarray(q), K,
                               filter=SampleFilter.from_mask(mask))
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        # the implicit all-ones buffer is the unfiltered identity
        d3, i3 = ex.search_bucket(jnp.asarray(q), NQ, K)
        d4, i4 = ivf_pq.search(mres, FULL, pq_index, jnp.asarray(q), K)
        np.testing.assert_array_equal(np.asarray(i3), np.asarray(i4))
        assert ex.operating_knobs(0)["filtered"] is True

    def test_zero_recompiles_across_varying_filters(self, mres, pq_index,
                                                    dataset):
        _, q, _ = dataset
        rng = np.random.default_rng(5)
        with obs.collecting():
            ex = serving.Executor(mres, "ivf_pq", pq_index, ks=(K,),
                                  max_batch=NQ, search_params=FULL,
                                  warm="jit", filter_rows=N)
            warm = query_filter_words(
                SampleFilter.from_mask(rng.random((NQ, N)) < 0.5), NQ, "t")
            ex.search_bucket(jnp.asarray(q), NQ, K,
                             filter_words=warm)[0].block_until_ready()
            c0 = obs.registry().counter("xla.compiles").value
            for _ in range(6):
                fw = query_filter_words(
                    SampleFilter.from_mask(rng.random((NQ, N)) < 0.2),
                    NQ, "t")
                ex.search_bucket(jnp.asarray(q), NQ, K,
                                 filter_words=fw)[0].block_until_ready()
            c1 = obs.registry().counter("xla.compiles").value
        assert c1 - c0 == 0, "filters are data, not shape"

    def test_server_tenant_isolation_and_composition(self, mres, pq_index,
                                                     dataset):
        _, q, _ = dataset
        tenants = TenantFilter(ranges={"a": (0, 1000), "b": (1000, N)},
                               n_rows=N)
        ex = serving.Executor(mres, "ivf_pq", pq_index, ks=(K,),
                              max_batch=NQ, search_params=FULL,
                              warm="jit", filter_rows=N)
        cfg = serving.ServerConfig(max_batch=NQ, max_wait_us=500.0,
                                   tenants=tenants)
        with serving.Server(ex, cfg) as srv:
            _, i_a = srv.search(q[:3], K, tenant="a", timeout=60)
            assert ((i_a >= 0) & (i_a < 1000)).all()
            _, i_b = srv.search(q[:3], K, tenant="b", timeout=60)
            assert ((i_b >= 1000) & (i_b < N)).all()
            # request filter ANDs with the namespace: even ids only
            even = np.arange(N) % 2 == 0
            _, i_e = srv.search(
                q[:3], K, tenant="a", timeout=60,
                filter=SampleFilter.from_mask(even[None]))
            assert ((i_e % 2 == 0) & (i_e < 1000)).all()
            with pytest.raises(LogicError):
                srv.search(q[:3], K, tenant="ghost", timeout=60)

    def test_filter_on_unconfigured_executor_rejected(self, mres,
                                                      pq_index, dataset):
        _, q, _ = dataset
        ex = serving.Executor(mres, "ivf_pq", pq_index, ks=(K,),
                              max_batch=NQ, search_params=FULL,
                              warm="jit")
        cfg = serving.ServerConfig(max_batch=NQ, max_wait_us=500.0)
        with serving.Server(ex, cfg) as srv:
            with pytest.raises(LogicError):
                srv.search(q[:2], K, timeout=60,
                           filter=np.ones(N, bool))


# ---------------------------------------------------------------------------
# distributed: the routed dispatch preserves the parity contract


class TestDistributedFiltered:
    @pytest.fixture(scope="class")
    def session(self):
        import jax
        from raft_tpu.comms import CommsSession
        devs = jax.devices()
        if len(devs) < 8:
            pytest.skip("needs 8 devices")
        mesh = jax.sharding.Mesh(np.asarray(devs[:8]), ("data",))
        s = CommsSession(mesh=mesh, axis_name="data").init()
        yield s
        s.destroy()

    @pytest.fixture(scope="class")
    def dist(self, session):
        from raft_tpu.distributed import ann
        rng = np.random.default_rng(0)
        n, dim = 4096, 16
        db = rng.normal(size=(n, dim)).astype(np.float32)
        q = rng.normal(size=(6, dim)).astype(np.float32)
        handle = session.worker_handle(seed=0)
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=8,
                                    kmeans_n_iters=4)
        ridx = ann.build(handle, params, db, placement="by_list")
        mask = rng.random((6, n)) < 0.3
        return handle, ridx, q, mask

    def test_routed_full_probe_bit_identical(self, dist):
        from raft_tpu.distributed import ann
        handle, ridx, q, mask = dist
        sp_full = ann.ground_truth_params(ridx)
        d1, i1, stats = ann.search(handle, sp_full, ridx, q, K,
                                   filter=SampleFilter.from_mask(mask),
                                   return_stats=True)
        i1 = np.asarray(i1)
        d_u, i_u = ann.search(handle, sp_full, ridx, q, 512)
        ref_d, ref_i = posthoc_reference(d_u, i_u, mask, K)
        np.testing.assert_array_equal(i1, ref_i)
        np.testing.assert_allclose(np.asarray(d1), ref_d, atol=1e-5)
        # per-shard admitted-row counters ride along
        adm = stats["admitted_rows"]
        assert adm.shape == (8,) and (adm >= 0).all()

    def test_routed_grouped_matches_lut(self, dist):
        from raft_tpu.distributed import ann
        handle, ridx, q, mask = dist
        filt = SampleFilter.from_mask(mask)
        sp_l = ann.ground_truth_params(ridx)
        _, i_l = ann.search(handle, sp_l, ridx, q, K, filter=filt)
        sp_f = ivf_pq.SearchParams(n_probes=16, scan_mode="fused")
        _, i_f = ann.search(handle, sp_f, ridx, q, K, filter=filt)
        np.testing.assert_array_equal(np.asarray(i_f), np.asarray(i_l))

    def test_data_parallel_admits_only(self, dist, session):
        from raft_tpu.distributed import ann
        handle, _, q, mask = dist
        rng = np.random.default_rng(0)
        db = rng.normal(size=(4096, 16)).astype(np.float32)
        didx = ann.build(handle, ivf_pq.IndexParams(
            n_lists=16, pq_dim=8, kmeans_n_iters=4), db)
        filt = SampleFilter.from_mask(mask)
        _, i3 = ann.search(handle, ivf_pq.SearchParams(
            n_probes=16, scan_mode="lut"), didx, q, K, filter=filt)
        i3 = np.asarray(i3)
        assert all(mask[qi, ii] for qi in range(6)
                   for ii in i3[qi] if ii >= 0)
