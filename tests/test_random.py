"""Random module tests (reference analogue: cpp/test/random/)."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import random as rrandom


class TestRngState:
    def test_deterministic_and_advancing(self):
        s1 = rrandom.RngState(5)
        s2 = rrandom.RngState(5)
        a = np.asarray(rrandom.uniform(s1, (100,)))
        b = np.asarray(rrandom.uniform(s2, (100,)))
        np.testing.assert_array_equal(a, b)
        c = np.asarray(rrandom.uniform(s1, (100,)))
        assert not np.array_equal(a, c)  # state advanced


class TestDistributions:
    def test_uniform_range(self):
        x = np.asarray(rrandom.uniform(0, (10000,), low=2.0, high=5.0))
        assert x.min() >= 2.0 and x.max() < 5.0
        assert abs(x.mean() - 3.5) < 0.05

    def test_uniform_int(self):
        x = np.asarray(rrandom.uniformInt(0, (10000,), low=3, high=9))
        assert x.min() >= 3 and x.max() < 9

    def test_normal_moments(self):
        x = np.asarray(rrandom.normal(1, (50000,), mu=2.0, sigma=3.0))
        assert abs(x.mean() - 2.0) < 0.1
        assert abs(x.std() - 3.0) < 0.1

    def test_bernoulli(self):
        x = np.asarray(rrandom.bernoulli(2, (20000,), prob=0.3))
        assert abs(x.mean() - 0.3) < 0.02

    def test_exponential(self):
        x = np.asarray(rrandom.exponential(3, (50000,), lam=2.0))
        assert abs(x.mean() - 0.5) < 0.02

    def test_discrete_weights(self):
        w = jnp.asarray([0.1, 0.0, 0.9])
        x = np.asarray(rrandom.discrete(4, (20000,), w))
        assert not (x == 1).any()
        assert abs((x == 2).mean() - 0.9) < 0.02


class TestGenerators:
    def test_make_blobs_separable(self):
        X, y = rrandom.make_blobs(500, 8, n_clusters=3, cluster_std=0.1,
                                  seed=0)
        X, y = np.asarray(X), np.asarray(y)
        assert X.shape == (500, 8) and y.shape == (500,)
        assert set(np.unique(y)) <= {0, 1, 2}
        # within-cluster distance should be far below between-cluster
        centers = np.stack([X[y == i].mean(0) for i in range(3)])
        within = max(np.abs(X[y == i] - centers[i]).max() for i in range(3))
        between = np.abs(centers[0] - centers[1]).max()
        assert within < between

    def test_make_blobs_given_centers(self):
        centers = np.array([[0.0, 0.0], [100.0, 100.0]], np.float32)
        X, y = rrandom.make_blobs(200, 2, centers=centers, cluster_std=0.5,
                                  seed=1)
        X, y = np.asarray(X), np.asarray(y)
        np.testing.assert_allclose(X[y == 1].mean(0), [100, 100], atol=1.0)

    def test_make_regression_recoverable(self):
        X, y, coef = rrandom.make_regression(200, 5, noise=0.0, seed=2,
                                             shuffle=False)
        X, y, coef = np.asarray(X), np.asarray(y), np.asarray(coef)
        np.testing.assert_allclose(X @ coef[:, 0], y, rtol=1e-3, atol=1e-2)

    def test_sample_without_replacement_distinct(self):
        idx = np.asarray(rrandom.sample_without_replacement(3, 1000, 100))
        assert len(np.unique(idx)) == 100
        assert idx.max() < 1000

    def test_weighted_sampling_prefers_heavy(self):
        w = jnp.asarray(np.r_[np.full(50, 100.0), np.full(950, 0.001)])
        idx = np.asarray(rrandom.sample_without_replacement(5, 1000, 50,
                                                            weights=w))
        assert (idx < 50).mean() > 0.8

    def test_permute_is_permutation(self):
        data = np.arange(50, dtype=np.float32).reshape(50, 1)
        out, perm = rrandom.permute(6, jnp.asarray(data))
        np.testing.assert_array_equal(np.sort(np.asarray(out)[:, 0]),
                                      data[:, 0])
        np.testing.assert_array_equal(np.asarray(out)[:, 0], data[perm, 0])

    def test_rmat_shapes_and_bounds(self):
        theta = np.full((10, 4), 0.25, np.float32)
        src, dst = rrandom.rmat_rectangular_generator(7, theta, 8, 6, 1000)
        src, dst = np.asarray(src), np.asarray(dst)
        assert src.shape == (1000,) and dst.shape == (1000,)
        assert src.max() < 2**8 and dst.max() < 2**6
        assert src.min() >= 0 and dst.min() >= 0

    def test_rmat_skew(self):
        # heavily skewed theta → most edges land in low quadrant
        theta = np.tile(np.array([[0.9, 0.05, 0.04, 0.01]], np.float32),
                        (8, 1))
        src, dst = rrandom.rmat_rectangular_generator(8, theta, 8, 8, 5000)
        assert np.asarray(src).mean() < 50

    def test_multi_variable_gaussian(self):
        mean = jnp.asarray([1.0, -2.0])
        cov = jnp.asarray([[2.0, 0.6], [0.6, 1.0]])
        x = np.asarray(rrandom.multi_variable_gaussian(9, mean, cov, 30000))
        np.testing.assert_allclose(x.mean(0), [1, -2], atol=0.05)
        np.testing.assert_allclose(np.cov(x.T), np.asarray(cov), atol=0.1)
