"""Linalg tests (reference analogue: cpp/test/linalg/ — compute-vs-reference
on random data, numpy as the host reference)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import linalg

RNG = np.random.default_rng(12)


def randm(m, n, dtype=np.float32):
    return RNG.normal(size=(m, n)).astype(dtype)


class TestBlas:
    def test_gemm(self):
        a, b = randm(17, 9), randm(9, 13)
        np.testing.assert_allclose(linalg.gemm(a, b), a @ b, rtol=1e-5)

    def test_gemm_trans_alpha_beta(self):
        a, b, z = randm(9, 17), randm(9, 13), randm(17, 13)
        out = linalg.gemm(a, b, alpha=2.0, beta=0.5, z=z, trans_x=True)
        np.testing.assert_allclose(out, 2.0 * a.T @ b + 0.5 * z, rtol=1e-4)

    def test_gemv_axpy_dot(self):
        A, x = randm(8, 5), RNG.normal(size=5).astype(np.float32)
        np.testing.assert_allclose(linalg.gemv(A, x), A @ x, rtol=1e-5)
        y = RNG.normal(size=5).astype(np.float32)
        np.testing.assert_allclose(linalg.axpy(3.0, x, y), 3 * x + y, rtol=1e-5)
        np.testing.assert_allclose(linalg.dot(x, y), x @ y, rtol=1e-5)


class TestSolvers:
    def test_eig_dc(self, res):
        A = randm(12, 12)
        A = A @ A.T + 12 * np.eye(12, dtype=np.float32)
        w, v = linalg.eig_dc(res, A)
        np.testing.assert_allclose(np.asarray(v) @ np.diag(np.asarray(w)) @ np.asarray(v).T,
                                   A, atol=1e-3)
        assert np.all(np.diff(np.asarray(w)) >= -1e-5)  # ascending

    def test_svd_returns_v_not_vt(self, res):
        A = randm(10, 6)
        u, s, v = linalg.svd(res, A)
        np.testing.assert_allclose(np.asarray(u) @ np.diag(np.asarray(s)) @ np.asarray(v).T,
                                   A, atol=1e-4)

    def test_rsvd_approximates(self, res):
        # low-rank matrix: rsvd should nail it
        u0 = randm(60, 5)
        v0 = randm(5, 40)
        A = u0 @ v0
        u, s, v = linalg.rsvd(res, jnp.asarray(A), k=5, n_iter=6)
        recon = np.asarray(u) @ np.diag(np.asarray(s)) @ np.asarray(v).T
        np.testing.assert_allclose(recon, A, atol=1e-2)

    def test_qr(self, res):
        A = randm(9, 4)
        q = linalg.qr_get_q(res, A)
        np.testing.assert_allclose(np.asarray(q).T @ np.asarray(q), np.eye(4),
                                   atol=1e-5)

    def test_lstsq(self, res):
        A, x_true = randm(30, 4), RNG.normal(size=4).astype(np.float32)
        b = A @ x_true
        x = linalg.lstsq(res, A, b)
        np.testing.assert_allclose(np.asarray(x), x_true, atol=1e-3)

    def test_cholesky_rank_one_update(self, res):
        A = randm(6, 6)
        A = A @ A.T + 6 * np.eye(6, dtype=np.float32)
        v = RNG.normal(size=6).astype(np.float32)
        L = np.linalg.cholesky(A)
        L_upd = linalg.cholesky_rank_one_update(res, jnp.asarray(L), jnp.asarray(v))
        expected = np.linalg.cholesky(A + np.outer(v, v))
        np.testing.assert_allclose(np.asarray(L_upd), expected, atol=1e-3)


class TestEltwise:
    def test_named_ops(self):
        x, y = randm(4, 5), randm(4, 5)
        np.testing.assert_allclose(linalg.add(x, y), x + y)
        np.testing.assert_allclose(linalg.subtract(x, y), x - y)
        np.testing.assert_allclose(linalg.multiply(x, y), x * y)
        np.testing.assert_allclose(linalg.divide(x, y), x / y, rtol=1e-5)
        np.testing.assert_allclose(linalg.eltwise_sqrt(np.abs(x)),
                                   np.sqrt(np.abs(x)), rtol=1e-6)

    def test_map_reduce(self):
        x = randm(6, 6)
        out = linalg.map_reduce(lambda a: a * a, jnp.add, 0.0, jnp.asarray(x))
        np.testing.assert_allclose(float(out), float((x * x).sum()), rtol=1e-4)

    def test_matrix_vector_op(self):
        m = randm(5, 3)
        v = RNG.normal(size=3).astype(np.float32)
        out = linalg.matrix_vector_op(jnp.asarray(m), jnp.asarray(v), jnp.add)
        np.testing.assert_allclose(out, m + v[None, :], rtol=1e-6)
        v2 = RNG.normal(size=5).astype(np.float32)
        out2 = linalg.matrix_vector_op(jnp.asarray(m), jnp.asarray(v2),
                                       jnp.multiply, along_rows=False)
        np.testing.assert_allclose(out2, m * v2[:, None], rtol=1e-6)

    def test_map_offset(self):
        out = linalg.map_offset(lambda i: i * 2, (3, 4))
        np.testing.assert_array_equal(np.asarray(out),
                                      (np.arange(12) * 2).reshape(3, 4))


class TestReductions:
    def test_norms(self):
        x = randm(7, 5)
        np.testing.assert_allclose(linalg.row_norm(x), (x * x).sum(1), rtol=1e-5)
        np.testing.assert_allclose(linalg.row_norm(x, sqrt=True),
                                   np.sqrt((x * x).sum(1)), rtol=1e-5)
        np.testing.assert_allclose(linalg.col_norm(x, linalg.NormType.L1Norm),
                                   np.abs(x).sum(0), rtol=1e-5)
        np.testing.assert_allclose(
            linalg.norm(x, linalg.NormType.LinfNorm, along_rows=True),
            np.abs(x).max(1), rtol=1e-6)

    def test_normalize(self):
        x = randm(5, 8)
        out = np.asarray(linalg.normalize(jnp.asarray(x)))
        np.testing.assert_allclose((out * out).sum(1), np.ones(5), rtol=1e-5)

    def test_reduce_with_ops(self):
        x = randm(4, 6)
        out = linalg.reduce(jnp.asarray(x), main_op=jnp.abs, reduce_op="max")
        np.testing.assert_allclose(out, np.abs(x).max(1), rtol=1e-6)

    def test_reduce_rows_by_key(self):
        x = randm(10, 3)
        keys = RNG.integers(0, 4, size=10).astype(np.int32)
        out = np.asarray(linalg.reduce_rows_by_key(jnp.asarray(x),
                                                   jnp.asarray(keys), 4))
        expected = np.zeros((4, 3), np.float32)
        np.add.at(expected, keys, x)
        np.testing.assert_allclose(out, expected, atol=1e-5)

    def test_reduce_rows_by_key_weighted(self):
        x = randm(10, 3)
        keys = RNG.integers(0, 4, size=10).astype(np.int32)
        w = RNG.random(10).astype(np.float32)
        out = np.asarray(linalg.reduce_rows_by_key(jnp.asarray(x),
                                                   jnp.asarray(keys), 4,
                                                   weights=jnp.asarray(w)))
        expected = np.zeros((4, 3), np.float32)
        np.add.at(expected, keys, x * w[:, None])
        np.testing.assert_allclose(out, expected, atol=1e-5)

    def test_reduce_cols_by_key(self):
        x = randm(3, 8)
        keys = RNG.integers(0, 3, size=8).astype(np.int32)
        out = np.asarray(linalg.reduce_cols_by_key(jnp.asarray(x),
                                                   jnp.asarray(keys), 3))
        expected = np.zeros((3, 3), np.float32)
        for j, k in enumerate(keys):
            expected[:, k] += x[:, j]
        np.testing.assert_allclose(out, expected, atol=1e-5)

    def test_mse(self):
        a, b = randm(6, 6), randm(6, 6)
        np.testing.assert_allclose(float(linalg.mean_squared_error(a, b)),
                                   ((a - b) ** 2).mean(), rtol=1e-5)
