"""Matrix primitive tests (reference analogue: cpp/test/matrix/, incl. the
select_k param grids of cpp/internal/raft_internal/matrix/select_k.cuh)."""

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import matrix
from raft_tpu.matrix.select_k import _TILE_LEN

RNG = np.random.default_rng(7)


class TestSelectK:
    @pytest.mark.parametrize("batch,length,k", [
        (1, 10, 1), (4, 100, 5), (16, 1000, 37), (3, 257, 256),
        (2, 40000, 64),  # exercises the tiled (radix-analogue) path
    ])
    @pytest.mark.parametrize("select_min", [True, False])
    def test_matches_numpy(self, batch, length, k, select_min):
        x = RNG.normal(size=(batch, length)).astype(np.float32)
        vals, idx = matrix.select_k(jnp.asarray(x), k, select_min=select_min)
        vals, idx = np.asarray(vals), np.asarray(idx)
        ref = np.sort(x, axis=1)[:, :k] if select_min \
            else -np.sort(-x, axis=1)[:, :k]
        np.testing.assert_allclose(vals, ref, rtol=1e-6)
        # indices actually point at the returned values
        np.testing.assert_allclose(np.take_along_axis(x, idx, axis=1), vals,
                                   rtol=1e-6)

    def test_in_idx_payload(self):
        x = RNG.normal(size=(2, 50)).astype(np.float32)
        payload = RNG.integers(0, 10**6, size=(2, 50)).astype(np.int64)
        vals, idx = matrix.select_k(jnp.asarray(x), 3,
                                    in_idx=jnp.asarray(payload))
        pos = np.argsort(x, axis=1)[:, :3]
        np.testing.assert_array_equal(np.asarray(idx),
                                      np.take_along_axis(payload, pos, axis=1))

    def test_sorted_output(self):
        x = RNG.normal(size=(5, 333)).astype(np.float32)
        vals, _ = matrix.select_k(jnp.asarray(x), 17)
        v = np.asarray(vals)
        assert np.all(np.diff(v, axis=1) >= 0)

    @pytest.mark.parametrize("k", [128, 256])
    @pytest.mark.parametrize("select_min", [True, False])
    def test_deep_batch_vs_numpy(self, k, select_min):
        """The ANN inner-loop shape: wide rows through the two-pass tiled
        path at batch 64 — exact agreement with the numpy oracle."""
        rng = np.random.default_rng(100 + k)
        x = rng.normal(size=(64, 131072)).astype(np.float32)
        vals, idx = matrix.select_k(jnp.asarray(x), k,
                                    select_min=select_min)
        vals, idx = np.asarray(vals), np.asarray(idx)
        ref = np.sort(x, axis=1)[:, :k] if select_min \
            else -np.sort(-x, axis=1)[:, :k]
        np.testing.assert_allclose(vals, ref, rtol=1e-6)
        np.testing.assert_allclose(np.take_along_axis(x, idx, axis=1),
                                   vals, rtol=1e-6)
        # no index appears twice in a row
        assert all(len(set(r.tolist())) == k for r in idx)

    @pytest.mark.parametrize("length", [
        _TILE_LEN - 1,       # single-pass, just under
        _TILE_LEN,           # single-pass, exactly at
        _TILE_LEN + 1,       # two-pass, 1-element tail tile
        _TILE_LEN + 129,     # two-pass, sub-k tail tile
        2 * _TILE_LEN,       # two-pass, full tiles
    ])
    def test_tile_boundary_lengths(self, length):
        """Lengths straddling _TILE_LEN: the tiled path's tail-tile
        padding must never surface padded slots in the result."""
        k = 128
        rng = np.random.default_rng(length)
        x = rng.normal(size=(4, length)).astype(np.float32)
        vals, idx = matrix.select_k(jnp.asarray(x), k)
        vals, idx = np.asarray(vals), np.asarray(idx)
        np.testing.assert_allclose(vals, np.sort(x, axis=1)[:, :k],
                                   rtol=1e-6)
        assert np.all(idx >= 0) and np.all(idx < length)
        np.testing.assert_allclose(np.take_along_axis(x, idx, axis=1),
                                   vals, rtol=1e-6)

    def test_ties_and_inf_sentinels(self):
        """Duplicated values and ±inf padding (the top-k merge sentinel
        regime): the selected multiset must equal the oracle's even when
        the winners are all ties, and inf rows must not poison ids."""
        k = 128
        length = _TILE_LEN + 777
        rng = np.random.default_rng(9)
        # heavy ties: values drawn from 17 distinct levels
        x = rng.integers(0, 17, size=(3, length)).astype(np.float32)
        # a row padded with +inf beyond a short valid prefix (ANN
        # sentinel shape), and one containing -inf entries
        x[1, 200:] = np.inf
        x[2, ::5] = -np.inf
        vals, idx = matrix.select_k(jnp.asarray(x), k)
        vals, idx = np.asarray(vals), np.asarray(idx)
        np.testing.assert_array_equal(vals, np.sort(x, axis=1)[:, :k])
        assert np.all(idx >= 0) and np.all(idx < length)
        np.testing.assert_array_equal(
            np.take_along_axis(x, idx, axis=1), vals)
        assert all(len(set(r.tolist())) == k for r in idx)


class TestOps:
    def test_gather_scatter(self):
        m = RNG.normal(size=(6, 3)).astype(np.float32)
        idx = np.array([4, 0, 2], np.int32)
        np.testing.assert_array_equal(
            np.asarray(matrix.gather(jnp.asarray(m), jnp.asarray(idx))), m[idx])
        upd = np.ones((3, 3), np.float32)
        out = np.asarray(matrix.scatter(jnp.asarray(m), jnp.asarray(idx),
                                        jnp.asarray(upd)))
        expected = m.copy()
        expected[idx] = 1.0
        np.testing.assert_array_equal(out, expected)

    def test_argminmax(self):
        m = RNG.normal(size=(5, 9)).astype(np.float32)
        np.testing.assert_array_equal(np.asarray(matrix.argmax(jnp.asarray(m))),
                                      m.argmax(1))
        np.testing.assert_array_equal(np.asarray(matrix.argmin(jnp.asarray(m))),
                                      m.argmin(1))

    def test_slice_reverse_diag(self):
        m = RNG.normal(size=(6, 6)).astype(np.float32)
        np.testing.assert_array_equal(
            np.asarray(matrix.slice_matrix(jnp.asarray(m), 1, 2, 4, 5)),
            m[1:4, 2:5])
        np.testing.assert_array_equal(
            np.asarray(matrix.reverse(jnp.asarray(m))), m[:, ::-1])
        np.testing.assert_array_equal(
            np.asarray(matrix.diagonal(jnp.asarray(m))), np.diagonal(m))

    def test_col_wise_sort(self):
        m = RNG.normal(size=(8, 4)).astype(np.float32)
        np.testing.assert_array_equal(
            np.asarray(matrix.col_wise_sort(jnp.asarray(m))), np.sort(m, axis=0))

    def test_sign_flip(self):
        m = RNG.normal(size=(7, 3)).astype(np.float32)
        out = np.asarray(matrix.sign_flip(jnp.asarray(m)))
        for j in range(3):
            assert out[np.abs(out[:, j]).argmax(), j] >= 0
        np.testing.assert_allclose(np.abs(out), np.abs(m), rtol=1e-6)

    def test_linewise_zero_threshold(self):
        m = RNG.normal(size=(4, 6)).astype(np.float32)
        v = RNG.normal(size=6).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(matrix.linewise_op(jnp.asarray(m), jnp.add,
                                          jnp.asarray(v))),
            m + v[None, :], rtol=1e-6)
        out = np.asarray(matrix.zero_small_values(jnp.asarray(m), 0.5))
        assert np.all((np.abs(out) >= 0.5) | (out == 0))


class TestRowDuplicateMask:
    def test_first_occurrence_wins(self):
        m = jnp.asarray([[3, 1, 3, 2, 1]])
        out = np.asarray(matrix.row_duplicate_mask(m))
        # later repeats flagged; the first occurrence of each value kept
        np.testing.assert_array_equal(out, [[False, False, True, False,
                                             True]])

    def test_ties_keep_exactly_one(self):
        m = jnp.asarray([[5, 5, 5, 5]])
        out = np.asarray(matrix.row_duplicate_mask(m))
        np.testing.assert_array_equal(out, [[False, True, True, True]])

    def test_all_equal_rows(self):
        m = jnp.full((3, 6), 7, jnp.int32)
        out = np.asarray(matrix.row_duplicate_mask(m))
        assert not out[:, 0].any()          # one survivor per row
        assert out[:, 1:].all()

    def test_single_column(self):
        m = jnp.asarray([[1], [1], [2]])
        out = np.asarray(matrix.row_duplicate_mask(m))
        assert not out.any()                # nothing to duplicate

    def test_no_duplicates(self):
        m = jnp.asarray([[4, 2, 9, 1]])
        assert not np.asarray(matrix.row_duplicate_mask(m)).any()

    def test_rows_independent(self):
        m = jnp.asarray([[1, 2, 3], [1, 1, 3]])
        out = np.asarray(matrix.row_duplicate_mask(m))
        np.testing.assert_array_equal(
            out, [[False, False, False], [False, True, False]])

    def test_matches_numpy_reference(self):
        x = RNG.integers(0, 8, size=(32, 24)).astype(np.int32)
        out = np.asarray(matrix.row_duplicate_mask(jnp.asarray(x)))
        for r in range(x.shape[0]):
            seen = set()
            for c in range(x.shape[1]):
                assert out[r, c] == (x[r, c] in seen)
                seen.add(x[r, c])
