"""MNMG algorithm tests on the virtual 8-device mesh (the reference's
LocalCUDACluster-without-a-cluster strategy, SURVEY.md §4) — distributed
results must match the single-device algorithms.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu import observability as obs
from raft_tpu.cluster import KMeansParams, kmeans
from raft_tpu.comms import CommsSession
from raft_tpu.distributed import kmeans as dist_kmeans
from raft_tpu.distributed import knn as dist_knn
from raft_tpu.neighbors import ivf_pq
from raft_tpu.random import make_blobs
from raft_tpu.serving import buckets as serving_buckets


@pytest.fixture
def session(mesh8):
    s = CommsSession(mesh=mesh8, axis_name="data").init()
    yield s
    s.destroy()


@pytest.fixture
def handle(session):
    return session.worker_handle(seed=0)


class TestDistributedKMeans:
    def test_matches_single_device(self, res, handle):
        X, _ = make_blobs(1600, 8, n_clusters=5, cluster_std=0.5, seed=2)
        X = np.asarray(X)
        c0 = X[:5].copy()
        params = KMeansParams(n_clusters=5, max_iter=50, tol=1e-6,
                              init=1)  # will be overridden by Array path
        from raft_tpu.cluster.kmeans_types import InitMethod
        params.init = InitMethod.Array
        dc, dinertia, dn = dist_kmeans.fit(handle, params, X,
                                           centroids=jnp.asarray(c0))
        sc, sinertia, sn = kmeans.fit(res, params, X, centroids=c0)
        # same init, same Lloyd updates -> same fixed point
        np.testing.assert_allclose(float(dinertia), float(sinertia),
                                   rtol=1e-3)
        # centroids equal up to ordering (same init -> same order)
        np.testing.assert_allclose(np.asarray(dc), np.asarray(sc),
                                   rtol=1e-3, atol=1e-3)

    def test_predict(self, handle):
        X, _ = make_blobs(800, 4, n_clusters=4, cluster_std=0.3, seed=3)
        X = np.asarray(X)
        from raft_tpu.cluster.kmeans_types import InitMethod
        params = KMeansParams(n_clusters=4, max_iter=30,
                              init=InitMethod.Array)
        c, _, _ = dist_kmeans.fit(handle, params, X,
                                  centroids=jnp.asarray(X[:4]))
        labels = dist_kmeans.predict(handle, params, X, c)
        labels = np.asarray(labels)
        d = ((X[:, None, :] - np.asarray(c)[None, :, :]) ** 2).sum(-1)
        np.testing.assert_array_equal(labels, d.argmin(1))

    def test_requires_comms(self, res):
        X = np.zeros((64, 4), np.float32)
        from raft_tpu.core.error import RaftError
        with pytest.raises(RaftError, match="comms"):
            dist_kmeans.fit(res, KMeansParams(n_clusters=2), X)


class TestDistributedKnn:
    def test_matches_single_device(self, res, handle):
        rng = np.random.default_rng(0)
        db = rng.normal(size=(1024, 16)).astype(np.float32)
        q = rng.normal(size=(32, 16)).astype(np.float32)
        dd, di = dist_knn.knn(handle, db, q, 8)
        from raft_tpu.neighbors import brute_force
        sd, si = brute_force.knn(res, db, q, 8,
                                 metric=0)  # L2Expanded
        np.testing.assert_allclose(np.asarray(dd), np.asarray(sd),
                                   rtol=1e-3, atol=1e-3)
        # ids may differ on exact ties only
        agree = (np.asarray(di) == np.asarray(si)).mean()
        assert agree > 0.95

    def test_inner_product(self, handle):
        rng = np.random.default_rng(1)
        db = rng.normal(size=(512, 8)).astype(np.float32)
        q = rng.normal(size=(16, 8)).astype(np.float32)
        from raft_tpu.distance.types import DistanceType
        dd, di = dist_knn.knn(handle, db, q, 4,
                              metric=DistanceType.InnerProduct)
        ip = q @ db.T
        ti = np.argsort(-ip, axis=1)[:, :4]
        np.testing.assert_array_equal(np.asarray(di), ti)

    def test_uneven_shards_rejected(self, handle):
        from raft_tpu.core.error import RaftError
        db = np.zeros((100, 4), np.float32)  # 100 % 8 != 0
        q = np.zeros((4, 4), np.float32)
        with pytest.raises(RaftError, match="divide"):
            dist_knn.knn(handle, db, q, 3)


class TestDistributedAnn:
    """Sharded IVF-PQ (the ANN bench 'multigpu' analogue): local indexes
    per shard + all_gather merge must find the same neighbors as a
    single-device index at the same total capacity."""

    def test_recall_matches_single_device(self, res, handle):
        from raft_tpu.distributed import ann as dist_ann
        from raft_tpu.neighbors import brute_force, ivf_pq
        X, _ = make_blobs(4096, 32, n_clusters=64, cluster_std=1.0, seed=7)
        X = jnp.asarray(X)
        Q = X[:64]
        # pq_dim=16 on 32-d keeps quantization fine enough that the 512-row
        # per-shard codebooks don't dominate the recall measurement
        params = ivf_pq.IndexParams(n_lists=8, pq_dim=16, kmeans_n_iters=5)
        dindex = dist_ann.build(handle, params, X)
        assert dindex.n_shards == 8
        sp = ivf_pq.SearchParams(n_probes=8)
        d, i = dist_ann.search(handle, sp, dindex, Q, 10)
        assert d.shape == (64, 10)
        # global ids must be valid and unique per row
        ii = np.asarray(i)
        assert ii.min() >= 0 and ii.max() < 4096
        for row in ii:
            assert len(set(row.tolist())) == 10
        # recall vs exact
        _, gt = brute_force.knn(res, X, Q, 10)
        gt = np.asarray(gt)
        rec = sum(len(set(a) & set(b)) for a, b in zip(ii, gt)) / gt.size
        assert rec >= 0.7   # PQ-limited, same bar as single-device tests

    def test_ids_are_global(self, handle):
        from raft_tpu.distributed import ann as dist_ann
        from raft_tpu.neighbors import ivf_pq
        rng = np.random.default_rng(0)
        X = jnp.asarray(rng.random((1024, 16), dtype=np.float32))
        params = ivf_pq.IndexParams(n_lists=4, pq_dim=4, kmeans_n_iters=3)
        dindex = dist_ann.build(handle, params, X)
        ids = np.asarray(dindex.list_indices)
        valid = ids[ids >= 0]
        # every row appears exactly once across all shards
        assert sorted(valid.tolist()) == list(range(1024))
        # shard s only holds ids from its own row range
        per = 1024 // 8
        for s in range(8):
            sv = ids[s][ids[s] >= 0]
            assert sv.min() >= s * per and sv.max() < (s + 1) * per

    def test_uneven_shards_rejected(self, handle):
        from raft_tpu.core.error import RaftError
        from raft_tpu.distributed import ann as dist_ann
        from raft_tpu.neighbors import ivf_pq
        X = jnp.zeros((1001, 8), jnp.float32)
        with pytest.raises(RaftError):
            dist_ann.build(handle, ivf_pq.IndexParams(n_lists=4), X)


class TestDistributedFlat:
    """Sharded IVF-Flat (multigpu parity for raft_ivf_flat)."""

    def test_recall_matches_single_device(self, res, handle):
        from raft_tpu.distributed import ann as dist_ann
        from raft_tpu.neighbors import brute_force, ivf_flat
        X, _ = make_blobs(4096, 32, n_clusters=64, cluster_std=1.0, seed=9)
        X = jnp.asarray(X)
        Q = X[:64]
        params = ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=5)
        dindex = dist_ann.build_flat(handle, params, X)
        assert dindex.n_shards == 8
        d, i = dist_ann.search_flat(handle,
                                    ivf_flat.SearchParams(n_probes=8),
                                    dindex, Q, 10)
        ii = np.asarray(i)
        assert ii.min() >= 0 and ii.max() < 4096
        for row in ii:
            assert len(set(row.tolist())) == 10
        _, gt = brute_force.knn(res, X, Q, 10)
        gt = np.asarray(gt)
        rec = sum(len(set(a) & set(b)) for a, b in zip(ii, gt)) / gt.size
        # exact distances within probed lists: all 8 local lists probed,
        # so the sharded search is exhaustive here
        assert rec >= 0.99

    def test_ids_are_global(self, handle):
        from raft_tpu.distributed import ann as dist_ann
        from raft_tpu.neighbors import ivf_flat
        rng = np.random.default_rng(1)
        X = jnp.asarray(rng.random((1024, 16), dtype=np.float32))
        dindex = dist_ann.build_flat(
            handle, ivf_flat.IndexParams(n_lists=4, kmeans_n_iters=3), X)
        ids = np.asarray(dindex.list_indices)
        valid = ids[ids >= 0]
        assert sorted(valid.tolist()) == list(range(1024))


class TestDistributedCagra:
    """Sharded CAGRA graphs + packed walks (the reference's multi-GPU
    seam, graph_core.cuh:333-369)."""

    def test_recall_vs_exact(self, res, handle):
        from raft_tpu.distributed import ann as dist_ann
        from raft_tpu.neighbors import brute_force, cagra
        rng = np.random.default_rng(4)
        n, dim, latent = 4096, 32, 8
        Z = rng.normal(size=(n, latent)).astype(np.float32)
        A = rng.normal(size=(latent, dim)).astype(np.float32) / np.sqrt(latent)
        X = jnp.asarray((Z @ A).astype(np.float32))
        Q = X[:64]
        params = cagra.IndexParams(intermediate_graph_degree=32,
                                   graph_degree=16)
        dindex = dist_ann.build_cagra(handle, params, X)
        assert dindex.n_shards == 8
        d, i = dist_ann.search_cagra(
            handle, cagra.SearchParams(itopk_size=32), dindex, Q, 10)
        ii = np.asarray(i)
        assert ii.min() >= 0 and ii.max() < n
        for row in ii:
            assert len(set(row.tolist())) == 10
        _, gt = brute_force.knn(res, X, Q, 10)
        gt = np.asarray(gt)
        rec = sum(len(set(a) & set(b)) for a, b in zip(ii, gt)) / gt.size
        assert rec >= 0.8

    def test_direct_walk_fallback(self, res, handle, monkeypatch):
        """When the packed table is infeasible (tiny byte gate), the
        sharded search must fall back to the exact direct walk and stay
        correct (the same route single-device search takes)."""
        from raft_tpu.distributed import ann as dist_ann
        from raft_tpu.neighbors import brute_force, cagra
        monkeypatch.setattr(cagra, "_WALK_TABLE_MAX_BYTES", 1)
        rng = np.random.default_rng(6)
        n, dim, latent = 2048, 32, 8
        Z = rng.normal(size=(n, latent)).astype(np.float32)
        A = rng.normal(size=(latent, dim)).astype(np.float32) / np.sqrt(latent)
        X = jnp.asarray((Z @ A).astype(np.float32))
        Q = X[:32]
        params = cagra.IndexParams(intermediate_graph_degree=32,
                                   graph_degree=16)
        dindex = dist_ann.build_cagra(handle, params, X)
        assert not dindex.use_walk
        d, i = dist_ann.search_cagra(
            handle, cagra.SearchParams(itopk_size=32), dindex, Q, 10)
        ii = np.asarray(i)
        assert ii.min() >= 0 and ii.max() < n
        _, gt = brute_force.knn(res, X, Q, 10)
        gt = np.asarray(gt)
        rec = sum(len(set(a) & set(b)) for a, b in zip(ii, gt)) / gt.size
        assert rec >= 0.7, rec


class TestRoutedAnn:
    """PR 8 tentpole: ``placement="by_list"`` index-parallel routing.

    Contracts under test: full-probe routed search is EXACTLY the
    single-index answer (hierarchical top-k over a disjoint list
    partition); per-shard scan work is ~1/n_shards of the probed rows
    (the acceptance tripwire); the candidate exchange is fixed at
    (k, nq) pairs per shard; a failed shard drops only its owned lists;
    the placement map and the whole routed index serialize round-trip.
    """

    N, DIM, NL, NQ, K = 2048, 32, 32, 16, 10

    @pytest.fixture(scope="class")
    def rhandle(self):
        devs = jax.devices()
        if len(devs) < 8:
            devs = jax.devices("cpu")
        if len(devs) < 8:
            pytest.skip("needs 8 devices")
        from raft_tpu.comms import CommsSession
        mesh = jax.sharding.Mesh(np.asarray(devs[:8]), ("data",))
        s = CommsSession(mesh=mesh, axis_name="data").init()
        yield s.worker_handle(seed=0)
        s.destroy()

    @pytest.fixture(scope="class")
    def data(self):
        rng = np.random.default_rng(7)
        db = rng.normal(size=(self.N, self.DIM)).astype(np.float32)
        q = rng.normal(size=(self.NQ, self.DIM)).astype(np.float32)
        return db, q

    @pytest.fixture(scope="class")
    def built(self, rhandle, data):
        from raft_tpu.distributed import ann
        db, _ = data
        params = ivf_pq.IndexParams(n_lists=self.NL, pq_dim=8,
                                    kmeans_n_iters=3,
                                    cache_reconstructions=True)
        base = ivf_pq.build(rhandle, params, db)
        return base, ann.shard_by_list(rhandle, base)

    @staticmethod
    def _recall(found, truth):
        hits = sum(len(set(f.tolist()) & set(t.tolist()))
                   for f, t in zip(found, truth))
        return hits / truth.size

    def test_full_probe_matches_single_index_exactly(self, rhandle, data,
                                                     built):
        from raft_tpu.core.outputs import raw
        from raft_tpu.distributed import ann
        _, q = data
        base, ridx = built
        sp = ivf_pq.SearchParams(n_probes=self.NL, scan_mode="recon")
        bd, bi = raw(ivf_pq.search)(rhandle, sp, base, q, self.K)
        rd, ri = ann.search(rhandle, sp, ridx, q, self.K)
        np.testing.assert_array_equal(np.asarray(ri), np.asarray(bi))
        np.testing.assert_allclose(np.asarray(rd), np.asarray(bd),
                                   rtol=1e-5, atol=1e-5)

    def test_straggler_injected_routed_search_is_exact(self, rhandle, data,
                                                       built, monkeypatch):
        """PR 12 satellite: a straggler-injected ROUTED search still
        merges the exact single-index answer — the scripted slow shard
        delays the merge (host-side pause in resilience.faults), it does
        not drop candidates."""
        from raft_tpu.core.outputs import raw
        from raft_tpu.distributed import ann
        from raft_tpu.resilience import FaultPlan, faults
        slept = []
        monkeypatch.setattr(faults, "_sleep", slept.append)
        _, q = data
        base, ridx = built
        sp = ivf_pq.SearchParams(n_probes=self.NL, scan_mode="recon")
        bd, bi = raw(ivf_pq.search)(rhandle, sp, base, q, self.K)
        plan = FaultPlan(seed=3).straggle_shard(1, delay=0.04)
        with plan.active():
            rd, ri = ann.search(rhandle, sp, ridx, q, self.K)
        np.testing.assert_array_equal(np.asarray(ri), np.asarray(bi))
        np.testing.assert_allclose(np.asarray(rd), np.asarray(bd),
                                   rtol=1e-5, atol=1e-5)
        assert slept == [0.04]

    def test_scan_work_and_gather_shape_tripwire(self, rhandle, data,
                                                 built):
        """Acceptance criterion: per-shard scanned candidates at the
        operating point stay under (probed_rows / n_shards) * 1.5 and
        the exchange is the fixed (n_shards, nq, k) pair block."""
        from raft_tpu.distributed import ann
        _, q = data
        _, ridx = built
        n_probes = 8
        sp = ivf_pq.SearchParams(n_probes=n_probes)
        _, _, stats = ann.search(rhandle, sp, ridx, q, self.K,
                                 return_stats=True)
        cap = ridx.capacity
        probed_rows = self.NQ * n_probes * cap
        bound = probed_rows / ridx.n_shards * 1.5
        assert stats["gather_shape"] == (ridx.n_shards, self.NQ, self.K)
        assert int(stats["scanned_rows"].sum()) <= probed_rows
        assert int(stats["scanned_rows"].max()) <= bound, (
            f"placement imbalance: {stats['scanned_rows']} vs {bound}")

    def test_recall_parity_with_data_parallel(self, rhandle, data, built):
        from raft_tpu.distributed import ann
        from raft_tpu.neighbors import brute_force
        from raft_tpu.core.outputs import raw
        db, q = data
        _, ridx = built
        params = ivf_pq.IndexParams(n_lists=self.NL, pq_dim=8,
                                    kmeans_n_iters=3,
                                    cache_reconstructions=True)
        dp = ann.build(rhandle, params, db)  # data-parallel replica
        sp = ivf_pq.SearchParams(n_probes=8)
        _, truth = raw(brute_force.knn)(rhandle, db, q, self.K)
        _, ri = ann.search(rhandle, sp, ridx, q, self.K)
        _, di = ann.search(rhandle, sp, dp, q, self.K)
        r_routed = self._recall(np.asarray(ri), np.asarray(truth))
        r_dp = self._recall(np.asarray(di), np.asarray(truth))
        assert r_routed > r_dp - 0.1, (r_routed, r_dp)

    def test_build_by_list_entry_point(self, rhandle, data):
        from raft_tpu.distributed import ann
        db, q = data
        params = ivf_pq.IndexParams(n_lists=self.NL, pq_dim=8,
                                    kmeans_n_iters=3,
                                    cache_reconstructions=True)
        idx = ann.build(rhandle, params, db, placement="by_list")
        assert isinstance(idx, ann.RoutedIndex)
        assert idx.n_shards == 8 and idx.n_lists == self.NL
        d, i, status = ann.search(rhandle, ivf_pq.SearchParams(n_probes=8),
                                  idx, q, self.K, return_status=True)
        assert np.asarray(i).min() >= 0
        np.testing.assert_array_equal(np.asarray(status),
                                      np.full(8, ann.SHARD_OK, np.int8))

    def test_placement_roundtrip(self, rhandle, built):
        import io
        from raft_tpu.distributed import ann
        _, ridx = built
        buf = io.BytesIO()
        ann.placement_to_stream(rhandle, buf, ridx.placement)
        buf.seek(0)
        back = ann.placement_from_stream(rhandle, buf)
        np.testing.assert_array_equal(back.owner, ridx.placement.owner)
        np.testing.assert_array_equal(back.local_slot,
                                      ridx.placement.local_slot)
        assert back.n_shards == ridx.placement.n_shards
        assert back.n_local == ridx.placement.n_local
        assert back.generation == ridx.placement.generation

    def test_routed_serialization_roundtrip(self, rhandle, data, built):
        import io
        from raft_tpu.distributed import ann
        _, q = data
        _, ridx = built
        buf = io.BytesIO()
        ann.serialize_routed(rhandle, buf, ridx)
        buf.seek(0)
        back = ann.deserialize_routed(rhandle, buf)
        sp = ivf_pq.SearchParams(n_probes=self.NL)
        d1, i1 = ann.search(rhandle, sp, ridx, q, self.K)
        d2, i2 = ann.search(rhandle, sp, back, q, self.K)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                                   rtol=1e-6)
        np.testing.assert_array_equal(back.placement.owner,
                                      ridx.placement.owner)

    def test_failed_shard_drops_only_owned_lists(self, rhandle, data,
                                                 built):
        from raft_tpu.core.outputs import raw
        from raft_tpu.distributed import ann
        from raft_tpu.neighbors import brute_force
        db, q = data
        base, ridx = built
        dead = 3
        sp = ivf_pq.SearchParams(n_probes=self.NL)
        d, i, status = ann.search(rhandle, sp, ridx, q, self.K,
                                  failed_shards=[dead],
                                  return_status=True)
        expect = np.full(8, ann.SHARD_OK, np.int8)
        expect[dead] = ann.SHARD_FAILED
        np.testing.assert_array_equal(np.asarray(status), expect)
        # ids living in the dead shard's owned lists must not appear
        li = np.asarray(base.list_indices)
        owned = ridx.placement.shard_lists(dead)
        lost = set(li[owned][li[owned] >= 0].ravel().tolist())
        found = set(np.asarray(i).ravel().tolist()) - {-1}
        assert not (found & lost)
        # graceful degradation: recall drops by roughly the dead shard's
        # owned share, not to a cliff
        _, truth = raw(brute_force.knn)(rhandle, db, q, self.K)
        rec = self._recall(np.asarray(i), np.asarray(truth))
        assert rec > 0.5, rec

    def test_scan_mode_fallback_reported_in_status(self, rhandle, data,
                                                   built):
        from raft_tpu.distributed import ann
        _, q = data
        _, ridx = built
        # round 10: fused lowers under shard_map at static group
        # capacity — shards report plain SHARD_OK, not FALLBACK
        sp = ivf_pq.SearchParams(n_probes=8, scan_mode="fused")
        _, i, status = ann.search(rhandle, sp, ridx, q, self.K,
                                  return_status=True)
        np.testing.assert_array_equal(
            np.asarray(status), np.full(8, ann.SHARD_OK, np.int8))
        assert np.asarray(i).min() >= 0
        # recon8 stays a genuine lowering under the routed path and
        # keeps the FALLBACK status visible to callers
        sp = ivf_pq.SearchParams(n_probes=8, scan_mode="recon8")
        _, i, status = ann.search(rhandle, sp, ridx, q, self.K,
                                  return_status=True)
        np.testing.assert_array_equal(
            np.asarray(status),
            np.full(8, ann.SHARD_OK_FALLBACK, np.int8))
        # fallback is a reporting change only: results still valid
        assert np.asarray(i).min() >= 0

    def test_rebalance_placement_preserves_results(self, rhandle, data,
                                                   built):
        from raft_tpu.distributed import ann
        from raft_tpu.neighbors import mutate
        _, q = data
        _, ridx = built
        sp = ivf_pq.SearchParams(n_probes=self.NL)
        d1, i1 = ann.search(rhandle, sp, ridx, q, self.K)
        reb = ann.rebalance_placement(rhandle, ridx)
        assert reb.placement.generation == ridx.placement.generation + 1
        assert mutate.generation(reb) == mutate.generation(ridx) + 1
        d2, i2 = ann.search(rhandle, sp, reb, q, self.K)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    def test_aot_export_merges_to_live_answer(self, rhandle, data, built):
        from raft_tpu.core import aot
        from raft_tpu.distributed import ann
        from raft_tpu.matrix.select_k import select_k
        from raft_tpu.neighbors import grouped
        from raft_tpu.distance.types import DistanceType
        _, q = data
        _, ridx = built
        n_probes = 8
        sp = ivf_pq.SearchParams(n_probes=n_probes)
        ld, li = ann.search(rhandle, sp, ridx, q, self.K)
        outs = []
        for s in range(ridx.n_shards):
            buf = aot.export_ivf_pq_routed_search(
                rhandle, ridx, s, n_probes, self.K, self.NQ)
            fn = aot.load_search_fn(buf)
            ds, is_ = fn(jnp.asarray(q))
            outs.append((np.asarray(ds), np.asarray(is_)))
        all_d = jnp.asarray(np.stack([o[0] for o in outs], 0))
        all_i = jnp.asarray(np.stack([o[1] for o in outs], 0))
        md, mi = grouped.finalize_topk(
            all_d.transpose(1, 0, 2), all_i.transpose(1, 0, 2),
            self.NQ, self.K,
            ridx.metric != DistanceType.InnerProduct, False, select_k)
        np.testing.assert_array_equal(np.asarray(mi), np.asarray(li))
        np.testing.assert_allclose(np.asarray(md), np.asarray(ld),
                                   rtol=1e-5, atol=1e-5)

    def test_executor_swap_is_placement_barrier(self, rhandle, data,
                                                built):
        from raft_tpu.distributed import ann
        from raft_tpu.serving.executor import DistributedExecutor
        _, q = data
        _, ridx = built
        ex = DistributedExecutor(
            rhandle, ridx, ks=(self.K,), max_batch=16,
            search_params=ivf_pq.SearchParams(n_probes=8))
        ex.warmup()
        d1, i1 = ex.search_bucket(jnp.asarray(q), self.NQ, self.K)
        reb = ann.rebalance_placement(rhandle, ridx)
        ex.swap_index(reb)
        d2, i2 = ex.search_bucket(jnp.asarray(q), self.NQ, self.K)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))

    # ---- round 10: sync-free fused grouping under the routed path ----

    def test_routed_fused_full_probe_matches_single_index(self, rhandle,
                                                          data):
        """by_list fused at full probe == the single-index fused answer.

        Per-cluster codebooks keep the index out of the codes/LUT
        branch, so BOTH sides land on the same grouped recon twin — the
        comparison is formulation-for-formulation, not recall-level."""
        from raft_tpu.core.outputs import raw
        from raft_tpu.distributed import ann
        db, q = data
        params = ivf_pq.IndexParams(
            n_lists=self.NL, pq_dim=self.DIM, kmeans_n_iters=3,
            codebook_kind=ivf_pq.CodebookKind.PER_CLUSTER,
            cache_reconstructions=True)
        base = ivf_pq.build(rhandle, params, db)
        ridx = ann.shard_by_list(rhandle, base)
        assert ridx.list_code_lanes is None   # not codes-eligible
        sp = ivf_pq.SearchParams(n_probes=self.NL, scan_mode="fused")
        bd, bi = raw(ivf_pq.search)(rhandle, sp, base, q, self.K)
        rd, ri, status = ann.search(rhandle, sp, ridx, q, self.K,
                                    return_status=True)
        np.testing.assert_array_equal(
            np.asarray(status), np.full(8, ann.SHARD_OK, np.int8))
        np.testing.assert_array_equal(np.asarray(ri), np.asarray(bi))
        np.testing.assert_allclose(np.asarray(rd), np.asarray(bd),
                                   rtol=1e-5, atol=1e-5)

    def test_routed_fused_does_not_tick_lowering(self, rhandle, data,
                                                 built):
        """Round-10 acceptance: scan_mode="fused" on a by_list index no
        longer counts as a distributed lowering — the counter that used
        to tick on every fused routed request must stay silent."""
        from raft_tpu import observability as obs
        from raft_tpu.distributed import ann
        _, q = data
        _, ridx = built
        sp = ivf_pq.SearchParams(n_probes=8, scan_mode="fused")
        with obs.collecting():
            low0 = obs.registry().counter(
                "distributed.ann.scan_mode_lowered").value
            _, _, stats = ann.search(rhandle, sp, ridx, q, self.K,
                                     return_stats=True)
            lowered = obs.registry().counter(
                "distributed.ann.scan_mode_lowered").value - low0
        assert lowered == 0, "fused routed search reported a lowering"
        assert stats["scan_mode"] in ("grouped_recon", "fused_recon",
                                      "fused_codes")

    def test_routed_fused_overflow_redispatch_under_skew(
            self, rhandle, data, built, monkeypatch):
        """Calibrated-capacity protocol: a probe distribution wider than
        the estimate must tick ivf_pq.search.group_overflow and
        re-dispatch at the worst bound — results identical to the
        uncalibrated (always-worst) index."""
        import dataclasses
        from raft_tpu import observability as obs
        from raft_tpu.distributed import ann
        from raft_tpu.neighbors import grouped
        _, q = data
        _, ridx = built
        # drop the compile-cache quantum so the class-sized mesh can
        # actually exceed a tightened capacity (at the default 256 the
        # rounded capacity clamps to the worst bound at this scale)
        monkeypatch.setattr(grouped, "_GROUP_ROUND", 1)
        sp = ivf_pq.SearchParams(n_probes=8, scan_mode="fused")
        d0, i0 = ann.search(rhandle, sp, ridx, q, self.K)
        tight = dataclasses.replace(ridx, group_est=0.05)
        slots = ridx.local_centers.shape[1]
        cap, exact = grouped.group_capacity(self.NQ, 8, slots, est=0.05)
        worst, _ = grouped.group_capacity(self.NQ, 8, slots)
        assert not exact and cap < worst, (cap, worst)
        with obs.collecting():
            d1, i1 = ann.search(rhandle, sp, tight, q, self.K)
            n_over = obs.registry().counter(
                "ivf_pq.search.group_overflow").value
        assert n_over >= 1, "skewed batch must trip the overflow gate"
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))

    def test_routed_overflow_lands_flight_event(
            self, rhandle, data, built, monkeypatch):
        """The overflow re-dispatch is an anomaly: it must land in the
        always-on flight recorder with both capacities, even with
        metrics collection and tracing disabled."""
        import dataclasses
        from raft_tpu.distributed import ann
        from raft_tpu.neighbors import grouped
        from raft_tpu.observability import flight
        _, q = data
        _, ridx = built
        monkeypatch.setattr(grouped, "_GROUP_ROUND", 1)
        sp = ivf_pq.SearchParams(n_probes=8, scan_mode="fused")
        tight = dataclasses.replace(ridx, group_est=0.05)
        flight.clear()
        ann.search(rhandle, sp, tight, q, self.K)
        evs = flight.events("ivf_pq.group_overflow")
        assert len(evs) >= 1
        worst, _ = grouped.group_capacity(
            self.NQ, 8, ridx.local_centers.shape[1])
        assert evs[0]["attrs"]["worst"] == worst
        assert evs[0]["attrs"]["calibrated_groups"] < worst
        assert evs[0]["trace_id"] is None   # no ambient trace active

    def test_routed_serialization_carries_code_leaves_and_est(
            self, rhandle, built):
        """Routed envelope v2: lane-major code leaves, pq_bits and the
        calibrated estimate survive the round trip (v1 streams read back
        as recon-only / uncalibrated — always-correct defaults)."""
        import io
        from raft_tpu.distributed import ann
        _, ridx = built
        assert ridx.list_code_lanes is not None   # codes-eligible base
        buf = io.BytesIO()
        ann.serialize_routed(rhandle, buf, ridx)
        buf.seek(0)
        back = ann.deserialize_routed(rhandle, buf)
        assert back.pq_bits == ridx.pq_bits
        assert back.group_est == ridx.group_est
        np.testing.assert_array_equal(np.asarray(back.list_code_lanes),
                                      np.asarray(ridx.list_code_lanes))
        np.testing.assert_array_equal(np.asarray(back.list_code_rsq),
                                      np.asarray(ridx.list_code_rsq))
        np.testing.assert_array_equal(np.asarray(back.codebooks),
                                      np.asarray(ridx.codebooks))

    def test_serving_dispatch_zero_sync_steady_state(self, rhandle, data,
                                                     built):
        """Round-10 serving acceptance on the routed path: across warmed
        mixed-size batches under scan_mode="fused", steady state sees
        ZERO XLA recompiles and ZERO overflow re-dispatches (the
        uncalibrated index runs the exact worst-bound regime, which
        never reads anything back)."""
        from raft_tpu.serving.executor import DistributedExecutor
        _, q = data
        _, ridx = built
        ex = DistributedExecutor(
            rhandle, ridx, ks=(self.K,), max_batch=16,
            search_params=ivf_pq.SearchParams(n_probes=8,
                                              scan_mode="fused"))
        qn = np.asarray(q)

        def dispatch(m):
            # host-side bucket assembly, exactly as the batcher does it
            # (a jnp.pad here would itself compile per novel (m, bucket)
            # pair — the recompile-hazard class of bug)
            b = serving_buckets.bucket_for(m, 16)
            buf = np.zeros((b, qn.shape[1]), qn.dtype)
            buf[:m] = qn[:m]
            return ex.search_bucket(jnp.asarray(buf), m, self.K)

        with obs.collecting():
            # the registry is global and cumulative — earlier tests
            # legitimately tick group_overflow, so assert deltas only
            over0 = obs.registry().counter(
                "ivf_pq.search.group_overflow").value
            ex.warmup()
            for m in (1, 3, 8, 16, 5, 2):
                dispatch(m)
            c0 = obs.registry().counter("xla.compiles").value
            for m in (2, 16, 1, 7, 4, 16, 3):
                dispatch(m)
            c1 = obs.registry().counter("xla.compiles").value
            n_over = obs.registry().counter(
                "ivf_pq.search.group_overflow").value - over0
        assert c1 == c0, f"{c1 - c0} recompiles in steady state"
        assert n_over == 0, "steady-state dispatch re-dispatched"


class TestReplicatedRouted:
    """PR 17 tentpole: replicated routed placement — recall-preserving
    shard failover, hedged straggler reads, health-tracked lifecycle.

    Contracts under test: with ``replication_factor=2`` and ANY single
    shard failed, full-probe routed search is BIT-IDENTICAL to the
    healthy run (the hierarchical-top-k exactness argument extends to a
    replica serving a superset of lists); each pair kill at r=3 stays
    exact; a kill at every lifecycle boundary (route / scan / gather /
    swap / catch-up) either fails over exactly or degrades gracefully
    with the documented status + flight trail; failover and readmission
    trigger ZERO steady-state recompiles (replica choice is data, not
    shape); hedged reads collapse a straggler's wait to the per-shard
    deadline without changing one bit of the answer.
    """

    N, DIM, NL, NQ, K = 2048, 32, 32, 16, 10

    @pytest.fixture(scope="class")
    def rhandle(self):
        devs = jax.devices()
        if len(devs) < 8:
            devs = jax.devices("cpu")
        if len(devs) < 8:
            pytest.skip("needs 8 devices")
        from raft_tpu.comms import CommsSession
        mesh = jax.sharding.Mesh(np.asarray(devs[:8]), ("data",))
        s = CommsSession(mesh=mesh, axis_name="data").init()
        yield s.worker_handle(seed=0)
        s.destroy()

    @pytest.fixture(scope="class")
    def data(self):
        rng = np.random.default_rng(7)
        db = rng.normal(size=(self.N, self.DIM)).astype(np.float32)
        q = rng.normal(size=(self.NQ, self.DIM)).astype(np.float32)
        return db, q

    @pytest.fixture(scope="class")
    def built(self, rhandle, data):
        from raft_tpu.distributed import ann
        db, _ = data
        params = ivf_pq.IndexParams(n_lists=self.NL, pq_dim=8,
                                    kmeans_n_iters=3,
                                    cache_reconstructions=True)
        base = ivf_pq.build(rhandle, params, db)
        return (base, ann.shard_by_list(rhandle, base),
                ann.shard_by_list(rhandle, base, replication_factor=2))

    # ---- placement invariants -------------------------------------------

    def test_replicated_placement_invariants(self):
        from raft_tpu.distributed import ann
        sizes = np.random.default_rng(11).integers(5, 200, self.NL)
        p1 = ann.compute_placement(sizes, 8)
        for r in (2, 3):
            p = ann.compute_placement(sizes, 8, replication_factor=r)
            assert p.owners.shape == (r, self.NL)
            # rank 0 IS the r=1 placement: a replicated index's healthy
            # routing is bit-identical to the unreplicated one
            np.testing.assert_array_equal(p.owner, p1.owner)
            np.testing.assert_array_equal(p.local_slot, p1.local_slot)
            # replicas of a list are never co-located
            for g in range(self.NL):
                assert len(set(p.owners[:, g].tolist())) == r
            # every rank's slots land inside the shard's slot range
            for s in range(8):
                ls = p.shard_lists(s)
                assert len(ls) == len(set(ls.tolist()))
                for rank in range(r):
                    mine = np.nonzero(p.owners[rank] == s)[0]
                    assert set(mine.tolist()) <= set(ls.tolist())

    def test_healthy_routing_covers_and_reports_residual(self):
        from raft_tpu.distributed import ann
        sizes = np.random.default_rng(12).integers(5, 200, self.NL)
        p = ann.compute_placement(sizes, 8, replication_factor=2)
        eo, es = p.healthy_routing((2,))
        assert 2 not in set(eo.tolist())   # fully covered at r=2
        # the replacement owner really owns the list at some rank, at
        # the slot the tables say
        for g in np.nonzero(p.owner == 2)[0]:
            rank = np.nonzero(p.owners[:, g] == eo[g])[0]
            assert rank.size == 1
            assert es[g] == p.slots[rank[0], g]
        # untouched lists keep the primary routing
        keep = p.owner != 2
        np.testing.assert_array_equal(eo[keep], p.owner[keep])
        np.testing.assert_array_equal(es[keep], p.local_slot[keep])

    def test_replication_needs_by_list_and_fits_mesh(self, rhandle, data):
        from raft_tpu.core.error import RaftError
        from raft_tpu.distributed import ann
        db, _ = data
        params = ivf_pq.IndexParams(n_lists=self.NL, pq_dim=8,
                                    kmeans_n_iters=3,
                                    cache_reconstructions=True)
        with pytest.raises(RaftError):
            ann.build(rhandle, params, db, replication_factor=2)  # by_row
        with pytest.raises(RaftError):
            ann.compute_placement(np.ones(self.NL, np.int64), 8,
                                  replication_factor=9)

    # ---- tentpole: failover exactness -----------------------------------

    def test_single_shard_failover_bit_identical(self, rhandle, data,
                                                 built):
        """Acceptance criterion: r=2, ANY single shard failed, full
        probe — bit-identical to the healthy run, the failed shard
        reported as replica-served (telemetry, not degradation)."""
        from raft_tpu.distributed import ann
        from raft_tpu.observability import flight
        _, q = data
        _, _, r2 = built
        sp = ivf_pq.SearchParams(n_probes=self.NL)
        d0, i0 = ann.search(rhandle, sp, r2, q, self.K)
        for dead in range(8):
            flight.clear()
            d1, i1, st = ann.search(rhandle, sp, r2, q, self.K,
                                    failed_shards=[dead],
                                    return_status=True)
            np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
            np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))
            st = np.asarray(st)
            assert st[dead] == ann.SHARD_REPLICA_SERVED
            ok = np.delete(st, dead)
            np.testing.assert_array_equal(
                ok, np.full(7, ann.SHARD_OK, np.int8))
            evs = flight.events("distributed.replica_failover")
            assert evs and evs[0]["attrs"]["covered"] == [dead]
            # a fully covered failover is NOT a degraded search
            assert not flight.events("distributed.degraded_search")

    def test_replicated_healthy_run_matches_single_index(self, rhandle,
                                                         data, built):
        from raft_tpu.core.outputs import raw
        from raft_tpu.distributed import ann
        _, q = data
        base, r1, r2 = built
        sp = ivf_pq.SearchParams(n_probes=self.NL, scan_mode="recon")
        bd, bi = raw(ivf_pq.search)(rhandle, sp, base, q, self.K)
        rd, ri = ann.search(rhandle, sp, r2, q, self.K)
        np.testing.assert_array_equal(np.asarray(ri), np.asarray(bi))
        # rank 0 == the r=1 placement: same routing, same answer
        d1, i1 = ann.search(rhandle, sp, r1, q, self.K)
        np.testing.assert_array_equal(np.asarray(ri), np.asarray(i1))

    def test_pair_kills_at_r3_bit_identical(self, rhandle, data):
        """Satellite: every shard PAIR killed at r=3 stays exact — two
        replicas lost still leaves one live owner per list."""
        import itertools
        from raft_tpu.distributed import ann
        db, q = data
        params = ivf_pq.IndexParams(n_lists=self.NL, pq_dim=8,
                                    kmeans_n_iters=3,
                                    cache_reconstructions=True)
        base = ivf_pq.build(rhandle, params, db)
        r3 = ann.shard_by_list(rhandle, base, replication_factor=3)
        sp = ivf_pq.SearchParams(n_probes=self.NL)
        d0, i0 = ann.search(rhandle, sp, r3, q, self.K)
        for a, b in itertools.combinations(range(8), 2):
            d1, i1, st = ann.search(rhandle, sp, r3, q, self.K,
                                    failed_shards=[a, b],
                                    return_status=True)
            np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
            st = np.asarray(st)
            assert (st[a] == ann.SHARD_REPLICA_SERVED
                    and st[b] == ann.SHARD_REPLICA_SERVED), (a, b, st)

    def test_fused_path_failover_bit_identical(self, rhandle, data,
                                               built):
        from raft_tpu.distributed import ann
        _, q = data
        _, _, r2 = built
        sp = ivf_pq.SearchParams(n_probes=self.NL, scan_mode="fused")
        d0, i0 = ann.search(rhandle, sp, r2, q, self.K)
        d1, i1 = ann.search(rhandle, sp, r2, q, self.K,
                            failed_shards=[5])
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))

    def test_uncovered_failure_degrades_gracefully(self, rhandle, data,
                                                   built):
        """When replicas do NOT cover the loss (a pair kill at r=2 can
        strand lists), the residual shards report SHARD_FAILED with the
        degraded-search flight event — the PR 8 contract, unchanged."""
        import itertools
        from raft_tpu.distributed import ann
        from raft_tpu.observability import flight
        _, q = data
        base, _, r2 = built
        sp = ivf_pq.SearchParams(n_probes=self.NL)
        p = r2.placement
        # find a pair that strands at least one list (both owners dead)
        stranded_pair = None
        for a, b in itertools.combinations(range(8), 2):
            eo, _ = p.healthy_routing((a, b))
            if set(eo.tolist()) & {a, b}:
                stranded_pair = (a, b)
                break
        assert stranded_pair is not None, "r=2 pair kill always covered?"
        a, b = stranded_pair
        flight.clear()
        d, i, st = ann.search(rhandle, sp, r2, q, self.K,
                              failed_shards=[a, b], return_status=True)
        st = np.asarray(st)
        eo, _ = p.healthy_routing((a, b))
        residual = sorted(set(eo.tolist()) & {a, b})
        for s in (a, b):
            want = (ann.SHARD_FAILED if s in residual
                    else ann.SHARD_REPLICA_SERVED)
            assert st[s] == want, (s, st)
        evs = flight.events("distributed.degraded_search")
        assert evs and evs[0]["attrs"]["failed"] == residual
        # stranded lists' ids are gone; everything else still answers
        li = np.asarray(base.list_indices)
        stranded = [g for g in range(self.NL)
                    if eo[g] in (a, b)]
        lost = set(li[stranded][li[stranded] >= 0].ravel().tolist())
        found = set(np.asarray(i).ravel().tolist()) - {-1}
        assert not (found & lost)

    # ---- kill matrix: lifecycle boundaries ------------------------------

    def test_kill_at_route_boundary_fails_over_this_search(
            self, rhandle, data, built):
        """A kill landing at the ROUTE boundary is seen by the same
        search's failed-set computation — it fails over immediately."""
        from raft_tpu.distributed import ann
        from raft_tpu.resilience import FaultPlan
        _, q = data
        _, _, r2 = built
        sp = ivf_pq.SearchParams(n_probes=self.NL)
        d0, i0 = ann.search(rhandle, sp, r2, q, self.K)
        plan = FaultPlan(seed=5).kill_shard_at("distributed.route", 3)
        with plan.active():
            d1, i1, st = ann.search(rhandle, sp, r2, q, self.K,
                                    return_status=True)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
        assert np.asarray(st)[3] == ann.SHARD_REPLICA_SERVED

    @pytest.mark.parametrize("site", ["distributed.scan",
                                      "distributed.gather"])
    def test_kill_at_scan_and_gather_boundaries(self, rhandle, data,
                                                built, site):
        """A kill landing mid-SCAN or at the GATHER keeps the in-flight
        search on its pre-kill routing (the shard's answer completes —
        the race a real failure also exposes); the NEXT search routes
        around the dead shard, bit-identically."""
        from raft_tpu.distributed import ann
        from raft_tpu.resilience import FaultPlan
        _, q = data
        _, _, r2 = built
        sp = ivf_pq.SearchParams(n_probes=self.NL)
        d0, i0 = ann.search(rhandle, sp, r2, q, self.K)
        plan = FaultPlan(seed=5).kill_shard_at(site, 6)
        with plan.active():
            d1, i1, st1 = ann.search(rhandle, sp, r2, q, self.K,
                                     return_status=True)
            d2, i2, st2 = ann.search(rhandle, sp, r2, q, self.K,
                                     return_status=True)
        # in-flight search: pre-kill routing, all shards OK
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
        np.testing.assert_array_equal(
            np.asarray(st1), np.full(8, ann.SHARD_OK, np.int8))
        # next search: failover, still bit-identical
        np.testing.assert_array_equal(np.asarray(i2), np.asarray(i0))
        assert np.asarray(st2)[6] == ann.SHARD_REPLICA_SERVED

    def test_kill_at_swap_and_catch_up_boundaries(self, rhandle, data,
                                                  built):
        """Kills landing during READMISSION itself: one shard dies while
        another's catch-up is gathering (catch-up boundary), another
        dies inside the swap barrier — every subsequent search stays
        bit-identical while replicas cover the loss."""
        from raft_tpu.distributed import ann, health
        from raft_tpu.resilience import FaultPlan
        _, q = data
        _, _, r2 = built
        sp = ivf_pq.SearchParams(n_probes=self.NL)
        d0, i0 = ann.search(rhandle, sp, r2, q, self.K)

        class _Server:
            def __init__(self):
                self.swapped = []

            def swap_index(self, idx):
                self.swapped.append(idx)

        srv = _Server()
        tr = health.HealthTracker(8, health.HealthConfig(
            suspect_after=1, fail_after=1, ok_to_clear=1, dwell_s=0.0))
        tr.note_timeout(2)
        tr.note_timeout(2)
        assert tr.state(2) == health.FAILED
        plan = (FaultPlan(seed=5)
                .kill_shard_at("distributed.catch_up", 4)
                .kill_shard_at("distributed.swap", 7))
        with plan.active():
            caught = health.catch_up(rhandle, r2, 2, tracker=tr)
            assert tr.state(2) == health.CATCHING_UP
            assert health.readmit(rhandle, srv, caught, 2, tracker=tr)
            assert tr.state(2) == health.HEALTHY
            assert srv.swapped and srv.swapped[0] is caught
            # shards 4 and 7 died at the catch-up / swap boundaries;
            # the published index still answers bit-identically
            live = srv.swapped[0]
            d1, i1, st = ann.search(rhandle, sp, live, q, self.K,
                                    return_status=True)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
        st = np.asarray(st)
        assert st[4] == ann.SHARD_REPLICA_SERVED
        assert st[7] == ann.SHARD_REPLICA_SERVED

    # ---- zero recompiles -------------------------------------------------

    def test_failover_and_readmission_zero_recompiles(self, rhandle,
                                                      data, built):
        """Replica choice is data, not shape: a fully covered failover
        reuses the warmed healthy executable (the static ``failed`` key
        stays ``()``), and a readmitted generation's search does too."""
        from raft_tpu.distributed import ann, health
        _, q = data
        _, _, r2 = built
        sp = ivf_pq.SearchParams(n_probes=self.NL)
        ann.search(rhandle, sp, r2, q, self.K)          # warm
        tr = health.HealthTracker(8, health.HealthConfig(
            suspect_after=1, fail_after=1, ok_to_clear=1, dwell_s=0.0))
        with obs.collecting():
            c0 = obs.registry().counter("xla.compiles").value
            _, i1 = ann.search(rhandle, sp, r2, q, self.K,
                               failed_shards=[1])
            c1 = obs.registry().counter("xla.compiles").value
            assert c1 == c0, f"{c1 - c0} recompiles on covered failover"
        # fail -> catch up -> readmit, then the steady-state search
        tr.note_timeout(1)
        tr.note_timeout(1)
        caught = health.catch_up(rhandle, r2, 1, tracker=tr)

        class _Server:
            def swap_index(self, idx):
                pass

        assert health.readmit(rhandle, _Server(), caught, 1, tracker=tr)
        ann.search(rhandle, sp, caught, q, self.K)      # first post-swap
        with obs.collecting():
            c0 = obs.registry().counter("xla.compiles").value
            _, i2 = ann.search(rhandle, sp, caught, q, self.K, health=tr)
            c1 = obs.registry().counter("xla.compiles").value
        assert c1 == c0, f"{c1 - c0} recompiles after readmission"
        np.testing.assert_array_equal(np.asarray(i2), np.asarray(i1))

    # ---- hedged reads ----------------------------------------------------

    def test_hedged_read_exact_wait_collapses_to_deadline(
            self, rhandle, data, built, monkeypatch):
        """Satellite: a 10x straggler behind a replica is hedged — the
        wait collapses from the scripted delay to the per-shard
        deadline, the answer stays bit-identical, and the shard reports
        replica-served with the hedged_read + shard_timeout trail."""
        from raft_tpu.distributed import ann
        from raft_tpu.observability import flight
        from raft_tpu.resilience import FaultPlan, faults
        slept = []
        monkeypatch.setattr(faults, "_sleep", slept.append)
        _, q = data
        _, _, r2 = built
        sp = ivf_pq.SearchParams(n_probes=self.NL)
        d0, i0 = ann.search(rhandle, sp, r2, q, self.K)
        flight.clear()
        plan = FaultPlan(seed=3).straggle_shard(2, delay=0.5)
        with plan.active():
            d1, i1, st = ann.search(rhandle, sp, r2, q, self.K,
                                    shard_deadline_s=0.05,
                                    return_status=True)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))
        assert slept == [0.05], slept
        assert np.asarray(st)[2] == ann.SHARD_REPLICA_SERVED
        hedges = flight.events("distributed.hedged_read")
        assert hedges and hedges[0]["attrs"]["shard"] == 2
        touts = flight.events("distributed.shard_timeout")
        assert touts and touts[0]["attrs"]["shard"] == 2

    def test_straggler_without_replica_waits_in_full(self, rhandle, data,
                                                     built, monkeypatch):
        """No covering replica -> the shard is UN-hedged: slow beats
        dropped, the full scripted delay is paid, results exact (the
        PR 12 contract survives the hedging rewrite)."""
        from raft_tpu.distributed import ann
        from raft_tpu.resilience import FaultPlan, faults
        slept = []
        monkeypatch.setattr(faults, "_sleep", slept.append)
        _, q = data
        _, r1, _ = built
        sp = ivf_pq.SearchParams(n_probes=self.NL)
        d0, i0 = ann.search(rhandle, sp, r1, q, self.K)
        plan = FaultPlan(seed=3).straggle_shard(1, delay=0.04)
        with plan.active():
            d1, i1 = ann.search(rhandle, sp, r1, q, self.K,
                                shard_deadline_s=0.01)
        assert slept == [0.04], slept
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))

    # ---- serialization ---------------------------------------------------

    def test_replicated_placement_serialization_roundtrip(self, rhandle,
                                                          built):
        import io
        from raft_tpu.distributed import ann
        _, _, r2 = built
        p = r2.placement
        buf = io.BytesIO()
        ann.placement_to_stream(rhandle, buf, p)
        buf.seek(0)
        back = ann.placement_from_stream(rhandle, buf)
        assert back.replication_factor == 2
        np.testing.assert_array_equal(back.owners, p.owners)
        np.testing.assert_array_equal(back.slots, p.slots)
        np.testing.assert_array_equal(back.owner, p.owner)
        np.testing.assert_array_equal(back.local_slot, p.local_slot)

    def test_replicated_routed_serialization_failover_survives(
            self, rhandle, data, built):
        """A deserialized replicated index re-places from the placement
        envelope alone — failover still bit-identical after reload."""
        import io
        from raft_tpu.distributed import ann
        _, q = data
        _, _, r2 = built
        buf = io.BytesIO()
        ann.serialize_routed(rhandle, buf, r2)
        buf.seek(0)
        back = ann.deserialize_routed(rhandle, buf)
        assert back.placement.replication_factor == 2
        sp = ivf_pq.SearchParams(n_probes=self.NL)
        d0, i0 = ann.search(rhandle, sp, r2, q, self.K)
        d1, i1, st = ann.search(rhandle, sp, back, q, self.K,
                                failed_shards=[0], return_status=True)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
        assert np.asarray(st)[0] == ann.SHARD_REPLICA_SERVED

    # ---- prewarm / AOT per replica rank ---------------------------------

    def test_aot_export_replica_rank_serves_failed_primary(
            self, rhandle, data, built):
        """Per-rank exports: merging every shard's rank-appropriate
        program reproduces the failover answer — the artifact set a
        deployment needs to survive a dead primary."""
        from raft_tpu.core import aot
        from raft_tpu.distributed import ann
        _, q = data
        _, _, r2 = built
        buf0 = aot.export_ivf_pq_routed_search(
            rhandle, r2, 0, 8, self.K, self.NQ)
        buf1 = aot.export_ivf_pq_routed_search(
            rhandle, r2, 0, 8, self.K, self.NQ, replica_rank=1)
        d0, i0 = aot.load_search_fn(buf0)(jnp.asarray(q))
        d1, i1 = aot.load_search_fn(buf1)(jnp.asarray(q))
        # rank tables differ -> the same shard answers for different
        # list subsets under the two programs
        assert not np.array_equal(np.asarray(i0), np.asarray(i1))
        with pytest.raises(Exception):
            aot.export_ivf_pq_routed_search(
                rhandle, r2, 0, 8, self.K, self.NQ, replica_rank=2)

    def test_executor_prewarms_per_replica_rank(self, rhandle, data,
                                                built):
        from raft_tpu.serving.executor import DistributedExecutor
        _, _, r2 = built
        ex = DistributedExecutor(
            rhandle, r2, ks=(self.K,), max_batch=16,
            search_params=ivf_pq.SearchParams(n_probes=8))
        n = ex.prewarm_shard_artifacts(scan_mode="recon")
        # buckets x ks x shards x ranks
        assert n == len(ex.buckets) * 1 * 8 * 2
