"""MNMG algorithm tests on the virtual 8-device mesh (the reference's
LocalCUDACluster-without-a-cluster strategy, SURVEY.md §4) — distributed
results must match the single-device algorithms.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.cluster import KMeansParams, kmeans
from raft_tpu.comms import CommsSession
from raft_tpu.distributed import kmeans as dist_kmeans
from raft_tpu.distributed import knn as dist_knn
from raft_tpu.random import make_blobs


@pytest.fixture
def session(mesh8):
    s = CommsSession(mesh=mesh8, axis_name="data").init()
    yield s
    s.destroy()


@pytest.fixture
def handle(session):
    return session.worker_handle(seed=0)


class TestDistributedKMeans:
    def test_matches_single_device(self, res, handle):
        X, _ = make_blobs(1600, 8, n_clusters=5, cluster_std=0.5, seed=2)
        X = np.asarray(X)
        c0 = X[:5].copy()
        params = KMeansParams(n_clusters=5, max_iter=50, tol=1e-6,
                              init=1)  # will be overridden by Array path
        from raft_tpu.cluster.kmeans_types import InitMethod
        params.init = InitMethod.Array
        dc, dinertia, dn = dist_kmeans.fit(handle, params, X,
                                           centroids=jnp.asarray(c0))
        sc, sinertia, sn = kmeans.fit(res, params, X, centroids=c0)
        # same init, same Lloyd updates -> same fixed point
        np.testing.assert_allclose(float(dinertia), float(sinertia),
                                   rtol=1e-3)
        # centroids equal up to ordering (same init -> same order)
        np.testing.assert_allclose(np.asarray(dc), np.asarray(sc),
                                   rtol=1e-3, atol=1e-3)

    def test_predict(self, handle):
        X, _ = make_blobs(800, 4, n_clusters=4, cluster_std=0.3, seed=3)
        X = np.asarray(X)
        from raft_tpu.cluster.kmeans_types import InitMethod
        params = KMeansParams(n_clusters=4, max_iter=30,
                              init=InitMethod.Array)
        c, _, _ = dist_kmeans.fit(handle, params, X,
                                  centroids=jnp.asarray(X[:4]))
        labels = dist_kmeans.predict(handle, params, X, c)
        labels = np.asarray(labels)
        d = ((X[:, None, :] - np.asarray(c)[None, :, :]) ** 2).sum(-1)
        np.testing.assert_array_equal(labels, d.argmin(1))

    def test_requires_comms(self, res):
        X = np.zeros((64, 4), np.float32)
        from raft_tpu.core.error import RaftError
        with pytest.raises(RaftError, match="comms"):
            dist_kmeans.fit(res, KMeansParams(n_clusters=2), X)


class TestDistributedKnn:
    def test_matches_single_device(self, res, handle):
        rng = np.random.default_rng(0)
        db = rng.normal(size=(1024, 16)).astype(np.float32)
        q = rng.normal(size=(32, 16)).astype(np.float32)
        dd, di = dist_knn.knn(handle, db, q, 8)
        from raft_tpu.neighbors import brute_force
        sd, si = brute_force.knn(res, db, q, 8,
                                 metric=0)  # L2Expanded
        np.testing.assert_allclose(np.asarray(dd), np.asarray(sd),
                                   rtol=1e-3, atol=1e-3)
        # ids may differ on exact ties only
        agree = (np.asarray(di) == np.asarray(si)).mean()
        assert agree > 0.95

    def test_inner_product(self, handle):
        rng = np.random.default_rng(1)
        db = rng.normal(size=(512, 8)).astype(np.float32)
        q = rng.normal(size=(16, 8)).astype(np.float32)
        from raft_tpu.distance.types import DistanceType
        dd, di = dist_knn.knn(handle, db, q, 4,
                              metric=DistanceType.InnerProduct)
        ip = q @ db.T
        ti = np.argsort(-ip, axis=1)[:, :4]
        np.testing.assert_array_equal(np.asarray(di), ti)

    def test_uneven_shards_rejected(self, handle):
        from raft_tpu.core.error import RaftError
        db = np.zeros((100, 4), np.float32)  # 100 % 8 != 0
        q = np.zeros((4, 4), np.float32)
        with pytest.raises(RaftError, match="divide"):
            dist_knn.knn(handle, db, q, 3)


class TestDistributedAnn:
    """Sharded IVF-PQ (the ANN bench 'multigpu' analogue): local indexes
    per shard + all_gather merge must find the same neighbors as a
    single-device index at the same total capacity."""

    def test_recall_matches_single_device(self, res, handle):
        from raft_tpu.distributed import ann as dist_ann
        from raft_tpu.neighbors import brute_force, ivf_pq
        X, _ = make_blobs(4096, 32, n_clusters=64, cluster_std=1.0, seed=7)
        X = jnp.asarray(X)
        Q = X[:64]
        # pq_dim=16 on 32-d keeps quantization fine enough that the 512-row
        # per-shard codebooks don't dominate the recall measurement
        params = ivf_pq.IndexParams(n_lists=8, pq_dim=16, kmeans_n_iters=5)
        dindex = dist_ann.build(handle, params, X)
        assert dindex.n_shards == 8
        sp = ivf_pq.SearchParams(n_probes=8)
        d, i = dist_ann.search(handle, sp, dindex, Q, 10)
        assert d.shape == (64, 10)
        # global ids must be valid and unique per row
        ii = np.asarray(i)
        assert ii.min() >= 0 and ii.max() < 4096
        for row in ii:
            assert len(set(row.tolist())) == 10
        # recall vs exact
        _, gt = brute_force.knn(res, X, Q, 10)
        gt = np.asarray(gt)
        rec = sum(len(set(a) & set(b)) for a, b in zip(ii, gt)) / gt.size
        assert rec >= 0.7   # PQ-limited, same bar as single-device tests

    def test_ids_are_global(self, handle):
        from raft_tpu.distributed import ann as dist_ann
        from raft_tpu.neighbors import ivf_pq
        rng = np.random.default_rng(0)
        X = jnp.asarray(rng.random((1024, 16), dtype=np.float32))
        params = ivf_pq.IndexParams(n_lists=4, pq_dim=4, kmeans_n_iters=3)
        dindex = dist_ann.build(handle, params, X)
        ids = np.asarray(dindex.list_indices)
        valid = ids[ids >= 0]
        # every row appears exactly once across all shards
        assert sorted(valid.tolist()) == list(range(1024))
        # shard s only holds ids from its own row range
        per = 1024 // 8
        for s in range(8):
            sv = ids[s][ids[s] >= 0]
            assert sv.min() >= s * per and sv.max() < (s + 1) * per

    def test_uneven_shards_rejected(self, handle):
        from raft_tpu.core.error import RaftError
        from raft_tpu.distributed import ann as dist_ann
        from raft_tpu.neighbors import ivf_pq
        X = jnp.zeros((1001, 8), jnp.float32)
        with pytest.raises(RaftError):
            dist_ann.build(handle, ivf_pq.IndexParams(n_lists=4), X)


class TestDistributedFlat:
    """Sharded IVF-Flat (multigpu parity for raft_ivf_flat)."""

    def test_recall_matches_single_device(self, res, handle):
        from raft_tpu.distributed import ann as dist_ann
        from raft_tpu.neighbors import brute_force, ivf_flat
        X, _ = make_blobs(4096, 32, n_clusters=64, cluster_std=1.0, seed=9)
        X = jnp.asarray(X)
        Q = X[:64]
        params = ivf_flat.IndexParams(n_lists=8, kmeans_n_iters=5)
        dindex = dist_ann.build_flat(handle, params, X)
        assert dindex.n_shards == 8
        d, i = dist_ann.search_flat(handle,
                                    ivf_flat.SearchParams(n_probes=8),
                                    dindex, Q, 10)
        ii = np.asarray(i)
        assert ii.min() >= 0 and ii.max() < 4096
        for row in ii:
            assert len(set(row.tolist())) == 10
        _, gt = brute_force.knn(res, X, Q, 10)
        gt = np.asarray(gt)
        rec = sum(len(set(a) & set(b)) for a, b in zip(ii, gt)) / gt.size
        # exact distances within probed lists: all 8 local lists probed,
        # so the sharded search is exhaustive here
        assert rec >= 0.99

    def test_ids_are_global(self, handle):
        from raft_tpu.distributed import ann as dist_ann
        from raft_tpu.neighbors import ivf_flat
        rng = np.random.default_rng(1)
        X = jnp.asarray(rng.random((1024, 16), dtype=np.float32))
        dindex = dist_ann.build_flat(
            handle, ivf_flat.IndexParams(n_lists=4, kmeans_n_iters=3), X)
        ids = np.asarray(dindex.list_indices)
        valid = ids[ids >= 0]
        assert sorted(valid.tolist()) == list(range(1024))


class TestDistributedCagra:
    """Sharded CAGRA graphs + packed walks (the reference's multi-GPU
    seam, graph_core.cuh:333-369)."""

    def test_recall_vs_exact(self, res, handle):
        from raft_tpu.distributed import ann as dist_ann
        from raft_tpu.neighbors import brute_force, cagra
        rng = np.random.default_rng(4)
        n, dim, latent = 4096, 32, 8
        Z = rng.normal(size=(n, latent)).astype(np.float32)
        A = rng.normal(size=(latent, dim)).astype(np.float32) / np.sqrt(latent)
        X = jnp.asarray((Z @ A).astype(np.float32))
        Q = X[:64]
        params = cagra.IndexParams(intermediate_graph_degree=32,
                                   graph_degree=16)
        dindex = dist_ann.build_cagra(handle, params, X)
        assert dindex.n_shards == 8
        d, i = dist_ann.search_cagra(
            handle, cagra.SearchParams(itopk_size=32), dindex, Q, 10)
        ii = np.asarray(i)
        assert ii.min() >= 0 and ii.max() < n
        for row in ii:
            assert len(set(row.tolist())) == 10
        _, gt = brute_force.knn(res, X, Q, 10)
        gt = np.asarray(gt)
        rec = sum(len(set(a) & set(b)) for a, b in zip(ii, gt)) / gt.size
        assert rec >= 0.8

    def test_direct_walk_fallback(self, res, handle, monkeypatch):
        """When the packed table is infeasible (tiny byte gate), the
        sharded search must fall back to the exact direct walk and stay
        correct (the same route single-device search takes)."""
        from raft_tpu.distributed import ann as dist_ann
        from raft_tpu.neighbors import brute_force, cagra
        monkeypatch.setattr(cagra, "_WALK_TABLE_MAX_BYTES", 1)
        rng = np.random.default_rng(6)
        n, dim, latent = 2048, 32, 8
        Z = rng.normal(size=(n, latent)).astype(np.float32)
        A = rng.normal(size=(latent, dim)).astype(np.float32) / np.sqrt(latent)
        X = jnp.asarray((Z @ A).astype(np.float32))
        Q = X[:32]
        params = cagra.IndexParams(intermediate_graph_degree=32,
                                   graph_degree=16)
        dindex = dist_ann.build_cagra(handle, params, X)
        assert not dindex.use_walk
        d, i = dist_ann.search_cagra(
            handle, cagra.SearchParams(itopk_size=32), dindex, Q, 10)
        ii = np.asarray(i)
        assert ii.min() >= 0 and ii.max() < n
        _, gt = brute_force.knn(res, X, Q, 10)
        gt = np.asarray(gt)
        rec = sum(len(set(a) & set(b)) for a, b in zip(ii, gt)) / gt.size
        assert rec >= 0.7, rec
