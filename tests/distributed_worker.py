"""Multi-process comms worker — the raft-dask LocalCUDACluster-test
analogue (reference: python/raft-dask/raft_dask/test/test_comms.py:45,
conftest.py).

Launched by test_multiprocess.py as N OS processes, each owning 2
virtual CPU devices.  Exercises the REAL multi-controller bootstrap:
``jax.distributed.initialize`` (the NCCL-uniqueId-rendezvous analogue),
a global mesh spanning both processes, CommsSession + collectives over
it, and one MNMG k-means fit.  Prints MULTIPROC_OK on success.
"""

import os
import sys

proc_id = int(sys.argv[1])
n_procs = int(sys.argv[2])
port = sys.argv[3]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

# the repo root must be importable BEFORE the first raft_tpu import —
# the launcher does not install the package, and the script-dir default
# on sys.path is tests/, not the repo root (this ordering bug made the
# whole test fail with ModuleNotFoundError whenever raft_tpu was not
# pip-installed)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# capability gate: a jax build without gloo CPU collectives (or with a
# broken multi-controller bootstrap) cannot run this worker at all —
# report UNSUPPORTED so the launcher skips instead of hard-failing
try:
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(coordinator_address=f"127.0.0.1:{port}",
                               num_processes=n_procs, process_id=proc_id)
except (RuntimeError, ValueError, NotImplementedError) as e:
    print(f"MULTIPROC_UNSUPPORTED: {type(e).__name__}: {e}", flush=True)
    sys.exit(0)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from raft_tpu.core.compat import shard_map  # noqa: E402

from raft_tpu.comms.comms import op_t  # noqa: E402
from raft_tpu.comms.session import CommsSession  # noqa: E402

P = jax.sharding.PartitionSpec

assert jax.process_count() == n_procs, jax.process_count()
devs = jax.devices()
n_dev = len(devs)
assert n_dev == 2 * n_procs, n_dev

session = CommsSession(devices=devs).init()
handle = session.worker_handle()
comms = session.comms()
mesh = session.mesh
assert handle.comms_initialized()
assert comms.get_size() == n_dev


def replicated(fn):
    """jit(shard_map) with replicated output — every process can read
    its local copy (multi-controller: np.asarray on a sharded global
    array is not allowed)."""
    return jax.jit(shard_map(fn, mesh=mesh, in_specs=(),
                                 out_specs=P(), check_vma=False))


# ---- collective self-tests over the cross-process mesh -------------------
out = replicated(
    lambda: comms.allreduce(jnp.ones((), jnp.float32), op_t.SUM)[None])()
assert float(np.asarray(out.addressable_data(0)).ravel()[0]) == n_dev, out

out = replicated(
    lambda: comms.allgather(
        jax.lax.axis_index(session.axis_name).astype(jnp.float32)[None]))()
got = np.asarray(out.addressable_data(0))
np.testing.assert_array_equal(got.ravel(),
                              np.arange(n_dev, dtype=np.float32))

# ---- one MNMG k-means fit over the global mesh ---------------------------
from raft_tpu.cluster.kmeans_types import InitMethod, KMeansParams  # noqa: E402
from raft_tpu.distributed import kmeans as dist_kmeans  # noqa: E402

rng = np.random.default_rng(0)
k = 4
centers_true = rng.normal(size=(k, 8)).astype(np.float32) * 6
labels_true = rng.integers(0, k, 256)
X_np = (centers_true[labels_true]
        + rng.normal(size=(256, 8)).astype(np.float32))

sharding = jax.sharding.NamedSharding(mesh, P(session.axis_name, None))
X = jax.make_array_from_callback((256, 8), sharding,
                                 lambda idx: X_np[idx])
# seed one point per true cluster (Array init; a degenerate seed can
# stall Lloyd in a local optimum, which is not what this test checks)
first = [int(np.argmax(labels_true == c)) for c in range(k)]
c0 = jnp.asarray(X_np[first])

params = KMeansParams(n_clusters=k, max_iter=10, tol=1e-4,
                      init=InitMethod.Array)
centroids, inertia, n_iter = dist_kmeans.fit(handle, params, X,
                                             centroids=c0)
c = np.asarray(centroids.addressable_data(0)
               if hasattr(centroids, "addressable_data") else centroids)
assert c.shape == (k, 8)
assert np.isfinite(c).all()
# every true center recovered to within the blob spread
d = ((c[:, None, :] - centers_true[None]) ** 2).sum(-1)
assert (d.min(0) < 4.0).all(), d.min(0)

# ---- p2p across the process boundary (reference: test_comms.py's
# send/recv suites run per transport; here the ppermute ring necessarily
# crosses the OS-process boundary on a 2-device-per-process mesh) ------


def _ring_shift():
    v = jax.lax.axis_index(session.axis_name).astype(jnp.float32)[None]
    s = comms.device_send(v, 1)          # rank r's value -> rank r+1
    return comms.allgather(s)


out = replicated(_ring_shift)()
got = np.asarray(out.addressable_data(0)).ravel()
np.testing.assert_array_equal(
    got, np.roll(np.arange(n_dev, dtype=np.float32), 1))


def _isend_irecv():
    v = 10.0 + jax.lax.axis_index(session.axis_name).astype(jnp.float32)
    sreq = comms.isend(v[None], [(r - 1) % n_dev for r in range(n_dev)],
                       tag=7)
    rreq = comms.irecv([(r + 1) % n_dev for r in range(n_dev)], tag=7)
    (data,) = comms.waitall([sreq, rreq])
    return comms.allgather(data)


out = replicated(_isend_irecv)()
got = np.asarray(out.addressable_data(0)).ravel()
np.testing.assert_array_equal(
    got, 10.0 + (np.arange(n_dev) + 1) % n_dev)

session.destroy()

# ---- 2D comm_split over the cross-process mesh (reference:
# test_comms.py:199-248 runs the full suite on sub-communicators; the
# (row, col) grid here spans both OS processes) ------------------------
from raft_tpu.comms import make_2d_session  # noqa: E402

assert n_dev % 2 == 0
s2 = make_2d_session(2, n_dev // 2, devices=devs).init()
c2 = s2.comms()
row = c2.comm_split("row")
col = c2.comm_split("col")
grp = c2.comm_split(grouped_by="row")    # same row -> communicate on col


def _grid():
    ri = jax.lax.axis_index("row").astype(jnp.float32)
    ci = jax.lax.axis_index("col").astype(jnp.float32)
    a = row.allreduce(ri, op_t.SUM)      # sum of row indices = 1
    b = col.allreduce(ci, op_t.SUM)      # sum of col indices
    g = grp.allreduce(ci, op_t.SUM)      # grouped_by row == along col
    return jnp.stack([a, b, g])[None]


out = jax.jit(shard_map(_grid, mesh=s2.mesh, in_specs=(),
                            out_specs=P(), check_vma=False))()
a, b, g = np.asarray(out.addressable_data(0)).ravel()
cols = n_dev // 2
assert a == 1.0, a
assert b == cols * (cols - 1) / 2, b
assert g == b, (g, b)
s2.destroy()

print(f"MULTIPROC_OK rank={proc_id} ndev={n_dev}", flush=True)
