"""graftlint: per-rule violation/clean fixtures, suppression, CLI, and
the live-tree tripwire (the analyzer's own acceptance bar: the shipped
tree must lint clean, so any regression fails here before it fails in
production behavior)."""

import json
import pathlib
import subprocess
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

from scripts.graftlint import (  # noqa: E402
    Project,
    build_registry,
    load_project,
    run_passes,
)
from scripts.graftlint.core import rule_docs  # noqa: E402


def lint(sources, rules=None):
    diags, _ = run_passes(Project.from_sources(sources), rules=rules)
    return diags


def rules_of(diags):
    return {d.rule for d in diags}


# ---------------------------------------------------------------------------
# recompile-hazard

class TestRecompileHazard:
    BAD_LEN = ("import jax.numpy as jnp\n"
               "def cut_batch(queue):\n"
               "    return jnp.zeros((len(queue), 4))\n")

    def test_len_derived_device_shape_flagged(self):
        diags = lint({"raft_tpu/serving/x.py": self.BAD_LEN})
        assert [d.rule for d in diags] == ["recompile-hazard"]
        assert diags[0].line == 3

    def test_host_numpy_sizing_clean(self):
        src = ("import numpy as np\n"
               "def cut_batch(queue):\n"
               "    return np.zeros((len(queue), 4))\n")
        assert lint({"raft_tpu/serving/x.py": src}) == []

    def test_scope_is_serving_and_distributed_only(self):
        # the same code is legal in build-time layers (ops/, neighbors/)
        assert lint({"raft_tpu/ops/x.py": self.BAD_LEN}) == []
        diags = lint({"raft_tpu/distributed/x.py": self.BAD_LEN})
        assert rules_of(diags) == {"recompile-hazard"}

    def test_jit_inside_hot_path_flagged(self):
        src = ("import jax\n"
               "def _dispatch(fn, q):\n"
               "    return jax.jit(fn)(q)\n")
        diags = lint({"raft_tpu/serving/x.py": src})
        assert [d.rule for d in diags] == ["recompile-hazard"]

    def test_module_scope_jit_clean(self):
        src = ("import jax\n"
               "def _impl(q):\n"
               "    return q\n"
               "_warm = jax.jit(_impl)\n"
               "def _dispatch(q):\n"
               "    return _warm(q)\n")
        assert lint({"raft_tpu/serving/x.py": src}) == []


# ---------------------------------------------------------------------------
# generation-discipline

class TestGenerationDiscipline:
    BAD = ("import dataclasses\n"
           "def rewrite_codes(index, codes):\n"
           "    return dataclasses.replace(index, codes=codes)\n")
    GOOD = ("import dataclasses\n"
            "from raft_tpu.neighbors import mutate as _mutate\n"
            "def rewrite_codes(index, codes):\n"
            "    out = dataclasses.replace(index, codes=codes)\n"
            "    return _mutate.next_generation(index, out)\n")

    def test_unbumped_replace_flagged(self):
        diags = lint({"raft_tpu/neighbors/x.py": self.BAD})
        assert [d.rule for d in diags] == ["generation-discipline"]

    def test_next_generation_bump_clean(self):
        assert lint({"raft_tpu/neighbors/x.py": self.GOOD}) == []

    def test_direct_generation_stamp_clean(self):
        src = ("def local_view(index, s):\n"
               "    out = Index(centers=index.centers[s])\n"
               "    out.generation = generation(index)\n"
               "    return out\n")
        assert lint({"raft_tpu/distributed/x.py": src}) == []

    def test_cache_key_without_generation_flagged(self):
        src = ("class ExecutableCache:\n"
               "    def get(self, index, batch):\n"
               "        key = (id(index), batch)\n"
               "        return self._entries.get(key)\n")
        diags = lint({"raft_tpu/serving/x.py": src})
        assert [d.rule for d in diags] == ["generation-discipline"]
        assert diags[0].line == 3

    def test_cache_key_with_generation_clean(self):
        src = ("class ExecutableCache:\n"
               "    def get(self, index, batch):\n"
               "        key = (id(index),\n"
               "               getattr(index, 'generation', 0), batch)\n"
               "        return self._entries.get(key)\n")
        assert lint({"raft_tpu/serving/x.py": src}) == []

    # -- fold publishing (PR 13: streaming-ingest memtable compaction) --

    def test_fold_mutating_index_leaf_in_place_flagged(self):
        src = ("def fold(self, base, rows):\n"
               "    base.list_data = rows\n"
               "    return base\n")
        diags = lint({"raft_tpu/serving/x.py": src})
        assert "generation-discipline" in rules_of(diags)
        assert any("in place" in d.message for d in diags)

    def test_fold_without_publish_flagged(self):
        src = ("def fold(self, base, rows, ids):\n"
               "    cand = extend(self.res, base, rows, ids)\n"
               "    return cand\n")
        diags = lint({"raft_tpu/serving/x.py": src})
        assert [d.rule for d in diags] == ["generation-discipline"]
        assert "swap_index" in diags[0].message

    def test_fold_via_swap_index_clean(self):
        src = ("def fold(self, base, rows, ids):\n"
               "    cand = extend(self.res, base, rows, ids)\n"
               "    self.server.swap_index(cand)\n"
               "    return cand\n")
        assert lint({"raft_tpu/serving/x.py": src}) == []

    def test_fold_via_generation_bump_clean(self):
        src = ("def fold(self, base, rows, ids):\n"
               "    cand = extend(self.res, base, rows, ids)\n"
               "    cand.generation = base.generation + 1\n"
               "    return cand\n")
        assert lint({"raft_tpu/serving/x.py": src}) == []

    def test_fold_rule_scoped_to_serving(self):
        # build-time layers fold freely (e.g. kmeans folds)
        src = ("def fold_batches(self, base, rows, ids):\n"
               "    return extend(self.res, base, rows, ids)\n")
        assert lint({"raft_tpu/ops/x.py": src}) == []

    # -- shard-local folds (round 19: placement-generation threading) --

    def test_fold_using_placement_without_generation_flagged(self):
        src = ("def fold(self, base, rows, ids, placement):\n"
               "    cand = extend(self.res, base, rows, ids)\n"
               "    cand.generation = base.generation + 1\n"
               "    routed = shard_by_list(self.handle, cand,\n"
               "                           placement=placement)\n"
               "    self.swap_index(routed)\n"
               "    return routed\n")
        diags = lint({"raft_tpu/serving/x.py": src})
        assert [d.rule for d in diags] == ["generation-discipline"]
        assert "placement generation" in diags[0].message

    def test_fold_threading_placement_generation_clean(self):
        src = ("def fold(self, base, rows, ids, placement):\n"
               "    cand = extend(self.res, base, rows, ids)\n"
               "    cand.generation = base.generation + 1\n"
               "    nxt = compute_placement(\n"
               "        sizes, n, generation=placement.generation + 1)\n"
               "    routed = shard_by_list(self.handle, cand,\n"
               "                           placement=nxt)\n"
               "    self.swap_index(routed)\n"
               "    return routed\n")
        assert lint({"raft_tpu/serving/x.py": src}) == []

    def test_placement_fold_rule_ignores_placement_free_folds(self):
        # the PR 13 single-writer fold never mentions the placement —
        # the shard-local rule must not fire on it
        src = ("def fold(self, base, rows, ids):\n"
               "    cand = extend(self.res, base, rows, ids)\n"
               "    cand.generation = base.generation + 1\n"
               "    return cand\n")
        assert lint({"raft_tpu/serving/x.py": src}) == []


# ---------------------------------------------------------------------------
# mask-seam

class TestMaskSeam:
    def test_exact_minus_one_compare_flagged(self):
        src = "def mask(ids):\n    return ids == -1\n"
        diags = lint({"raft_tpu/neighbors/x.py": src})
        assert [d.rule for d in diags] == ["mask-seam"]
        assert "tombstones" in diags[0].message

    def test_sign_test_clean(self):
        src = "def mask(ids):\n    return ids < 0\n"
        assert lint({"raft_tpu/neighbors/x.py": src}) == []

    def test_non_id_names_not_flagged(self):
        src = "def f(count):\n    return count == -1\n"
        assert lint({"raft_tpu/neighbors/x.py": src}) == []

    def test_inf_in_pallas_product_flagged(self):
        src = ("import jax.numpy as jnp\n"
               "def kernel(mask, d):\n"
               "    return d + mask * jnp.inf\n")
        diags = lint({"raft_tpu/ops/foo_pallas.py": src})
        assert [d.rule for d in diags] == ["mask-seam"]
        assert "3.0e38" in diags[0].message

    def test_finite_sentinel_in_pallas_clean(self):
        src = ("def kernel(mask, d):\n"
               "    return d + mask * 3.0e38\n")
        assert lint({"raft_tpu/ops/foo_pallas.py": src}) == []

    def test_inf_outside_pallas_clean(self):
        # inf is fine outside the one-hot-merge kernels (e.g. top-k
        # seeds in plain ops modules)
        src = ("import jax.numpy as jnp\n"
               "def seed(mask, d):\n"
               "    return d + mask * jnp.inf\n")
        assert lint({"raft_tpu/ops/foo.py": src}) == []

    def test_inf_at_staging_ring_write_flagged(self):
        src = ("import jax.numpy as jnp\n"
               "def kernel(stg_v):\n"
               "    stg_v[:] = jnp.full(stg_v.shape, jnp.inf, "
               "jnp.float32)\n")
        diags = lint({"raft_tpu/ops/foo_pallas.py": src})
        assert [d.rule for d in diags] == ["staging-ring"]
        assert "_ACC_WORST" in diags[0].message

    def test_rogue_sentinel_fill_flagged(self):
        # a huge float that is not the shared 3.0e38 breaks the
        # liveness test the merge and epilogue share
        src = ("import jax.numpy as jnp\n"
               "def kernel(acc_v):\n"
               "    acc_v[:] = jnp.full(acc_v.shape, 1.0e38, "
               "jnp.float32)\n")
        diags = lint({"raft_tpu/ops/foo_pallas.py": src})
        assert [d.rule for d in diags] == ["staging-ring"]
        assert "3.0e38" in diags[0].message

    def test_acc_worst_ring_fill_clean(self):
        src = ("import jax.numpy as jnp\n"
               "_ACC_WORST = 3.0e38\n"
               "def kernel(stg_v, acc_i):\n"
               "    stg_v[:] = jnp.full(stg_v.shape, _ACC_WORST, "
               "jnp.float32)\n"
               "    acc_i[:] = jnp.full(acc_i.shape, -1.0, "
               "jnp.float32)\n")
        assert lint({"raft_tpu/ops/foo_pallas.py": src}) == []

    def test_ring_rule_scoped_to_pallas(self):
        # plain ops modules stage with inf freely (no one-hot merge)
        src = ("import jax.numpy as jnp\n"
               "def f(stg_v):\n"
               "    stg_v[:] = jnp.full(stg_v.shape, jnp.inf, "
               "jnp.float32)\n")
        assert lint({"raft_tpu/ops/foo.py": src}) == []

    def test_inline_scratch_in_fused_module_flagged(self):
        src = ("import jax.experimental.pallas as pl\n"
               "def run(kern, tpu):\n"
               "    return pl.pallas_call(\n"
               "        kern,\n"
               "        scratch_shapes=[tpu.VMEM((8, 128), 'float32')],\n"
               "    )\n")
        diags = lint(
            {"raft_tpu/ops/pq_group_scan_pallas.py": src})
        assert [d.rule for d in diags] == ["scratch-budget"]
        assert "vmem_budget" in diags[0].message

    def test_budgeted_scratch_clean(self):
        src = ("import jax.experimental.pallas as pl\n"
               "from raft_tpu.ops import vmem_budget as vb\n"
               "def run(kern, k, kt, mw, nq_pad):\n"
               "    return pl.pallas_call(\n"
               "        kern,\n"
               "        scratch_shapes=vb.fused_scan_scratch(k, kt, mw, "
               "nq_pad),\n"
               "    )\n")
        assert lint(
            {"raft_tpu/ops/pq_group_scan_pallas.py": src}) == []

    def test_scratch_rule_scoped_to_fused_modules(self):
        # other kernels (kmeans, top-k) size scratch however they like
        src = ("import jax.experimental.pallas as pl\n"
               "def run(kern, tpu):\n"
               "    return pl.pallas_call(\n"
               "        kern,\n"
               "        scratch_shapes=[tpu.VMEM((8, 128), 'float32')],\n"
               "    )\n")
        assert lint({"raft_tpu/ops/kmeans_update_pallas.py": src}) == []


# ---------------------------------------------------------------------------
# admission-seam (round 20: filtered-search admission bits in kernels)

class TestAdmissionSeam:
    def test_admission_bit_in_product_flagged(self):
        # a rejected candidate scored 0*d = 0 would become the BEST hit
        src = ("def kernel(adm, d):\n"
               "    return d * adm\n")
        diags = lint({"raft_tpu/ops/foo_pallas.py": src})
        assert [d.rule for d in diags] == ["admission-seam"]
        assert "_ACC_WORST" in diags[0].message

    def test_admission_bit_in_dot_flagged(self):
        src = ("import jax.numpy as jnp\n"
               "def kernel(adm_block, oh):\n"
               "    return jnp.dot(oh, adm_block)\n")
        diags = lint({"raft_tpu/ops/foo_pallas.py": src})
        assert [d.rule for d in diags] == ["admission-seam"]

    def test_admission_select_to_inf_flagged(self):
        src = ("import jax.numpy as jnp\n"
               "def kernel(adm, d):\n"
               "    return jnp.where(adm > 0, d, jnp.inf)\n")
        diags = lint({"raft_tpu/ops/foo_pallas.py": src})
        assert [d.rule for d in diags] == ["admission-seam"]
        assert "3.0e38" in diags[0].message

    def test_admission_select_to_finite_sentinel_clean(self):
        src = ("import jax.numpy as jnp\n"
               "def kernel(adm, d):\n"
               "    return jnp.where(adm > 0, d, 3.0e38)\n")
        assert lint({"raft_tpu/ops/foo_pallas.py": src}) == []

    def test_admission_nonzero_constant_compare_flagged(self):
        # the unpack contract is 0 vs non-zero, not exactly-1
        src = ("def kernel(adm, invalid):\n"
               "    return invalid | (adm == 1)\n")
        diags = lint({"raft_tpu/ops/foo_pallas.py": src})
        assert [d.rule for d in diags] == ["admission-seam"]
        assert "non-zero" in diags[0].message

    def test_mask_fold_idiom_clean(self):
        # the blessed seam: fold into the validity mask, zero tests only
        src = ("def kernel(adm, invalid, ok):\n"
               "    invalid = invalid | (adm == 0)\n"
               "    ok = ok & (adm > 0)\n"
               "    return invalid, ok\n")
        assert lint({"raft_tpu/ops/foo_pallas.py": src}) == []

    def test_admission_rule_scoped_to_pallas(self):
        # host-side code packs/ANDs admission words however it likes
        src = ("def host(adm_words, scale):\n"
               "    return adm_words * scale\n")
        assert lint({"raft_tpu/filters/foo.py": src}) == []

    def test_unpack_shift_mask_clean(self):
        # the in-kernel unpack (shift/and on the packed ref) is not a
        # product seam
        src = ("def unpack(adm_ref, cap):\n"
               "    aw = adm_ref[0]\n"
               "    bits = (aw[:, :, None] >> 3) & 1\n"
               "    return bits\n")
        assert lint({"raft_tpu/ops/foo_pallas.py": src}) == []

    def test_admission_suppression_honored(self):
        src = ("def kernel(adm, d):\n"
               "    return d * adm"
               "  # graftlint: disable=admission-seam -- reason\n")
        assert lint({"raft_tpu/ops/foo_pallas.py": src}) == []


# ---------------------------------------------------------------------------
# boundary-guard

class TestBoundaryGuard:
    def test_unguarded_entry_point_flagged(self):
        src = ("def search(res, params, index, queries, k):\n"
               "    return queries\n")
        diags = lint({"raft_tpu/neighbors/x.py": src})
        assert [d.rule for d in diags] == ["boundary-guard"]

    def test_direct_validator_call_clean(self):
        src = ("from raft_tpu.integrity import boundary as _b\n"
               "def search(res, params, index, queries, k):\n"
               "    queries, ok = _b.check_matrix(queries, 'q', site='s')\n"
               "    return queries\n")
        assert lint({"raft_tpu/neighbors/x.py": src}) == []

    def test_same_module_delegation_clean(self):
        src = ("from raft_tpu.integrity.boundary import check_matrix\n"
               "def _impl(queries):\n"
               "    queries, ok = check_matrix(queries, 'q', site='s')\n"
               "    return queries\n"
               "def search(res, params, index, queries, k):\n"
               "    return _impl(queries)\n")
        assert lint({"raft_tpu/neighbors/x.py": src}) == []

    def test_serving_scans_class_methods(self):
        src = ("class Server:\n"
               "    def submit(self, queries):\n"
               "        return queries\n")
        diags = lint({"raft_tpu/serving/x.py": src})
        assert [d.rule for d in diags] == ["boundary-guard"]
        # ...but neighbors/cluster check module-level functions only
        assert lint({"raft_tpu/neighbors/x.py": src}) == []


# ---------------------------------------------------------------------------
# timing discipline (the former CI greps, now AST-accurate)

class TestTimingDiscipline:
    def test_raw_perf_counter_flagged(self):
        src = "import time\ndef f():\n    return time.perf_counter()\n"
        diags = lint({"raft_tpu/core/x.py": src})
        assert [d.rule for d in diags] == ["raw-perf-counter"]

    def test_from_import_alias_flagged(self):
        # the old grep missed "from time import perf_counter as clock"
        src = ("from time import perf_counter as clock\n"
               "def f():\n"
               "    return clock()\n")
        diags = lint({"raft_tpu/core/x.py": src})
        assert [d.rule for d in diags] == ["raw-perf-counter"]

    def test_observability_package_exempt(self):
        src = "import time\ndef f():\n    return time.perf_counter()\n"
        assert lint({"raft_tpu/observability/x.py": src}) == []

    def test_mention_in_docstring_clean(self):
        # the old grep false-positived on prose; the AST pass must not
        src = '"""never call time.perf_counter() or time.sleep(1)."""\n'
        assert lint({"raft_tpu/core/x.py": src}) == []

    def test_bare_sleep_flagged_outside_resilience(self):
        src = "import time\ndef f():\n    time.sleep(1)\n"
        diags = lint({"raft_tpu/serving/x.py": src})
        assert [d.rule for d in diags] == ["bare-sleep"]
        assert lint({"raft_tpu/resilience/x.py": src}) == []

    def test_monotonic_and_cond_wait_clean(self):
        src = ("import time\n"
               "def f(cond):\n"
               "    t = time.monotonic()\n"
               "    cond.wait(timeout=0.1)\n"
               "    return t\n")
        assert lint({"raft_tpu/serving/x.py": src}) == []


# ---------------------------------------------------------------------------
# registry-consistency

LIB = ("def _count(name):\n"
       "    registry().counter(name).inc()\n"
       "def admit(op):\n"
       "    registry().counter('serving.batch.admitted').inc()\n"
       "    _count('serving.batch.expired')\n"
       "    registry().counter(f'comms.{op}.calls').inc()\n"
       "def swap():\n"
       "    maybe_fail('serving.swap')\n")


class TestRegistryConsistency:
    def test_typoed_counter_assert_flagged(self):
        test = ("def test_x(snap):\n"
                "    assert snap['counters']['serving.batch.admited']\n")
        diags = lint({"raft_tpu/serving/obs.py": LIB,
                      "tests/test_x.py": test})
        assert [d.rule for d in diags] == ["registry-consistency"]
        assert "serving.batch.admited" in diags[0].message

    def test_known_names_and_prefixes_resolve(self):
        test = ("def test_x(snap, plan):\n"
                "    assert snap['counters']['serving.batch.admitted']\n"
                "    assert snap['counters'].get("
                "'serving.batch.expired', 0)\n"
                "    assert 'comms.p2p.calls' in snap['counters']\n"
                "    plan.at('serving.swap')\n")
        assert lint({"raft_tpu/serving/obs.py": LIB,
                     "tests/test_x.py": test}) == []

    def test_indirect_helper_names_register(self):
        # _count("serving.batch.expired") defines the name even though
        # the .counter() call site only sees the bare parameter
        test = ("def test_x(snap):\n"
                "    assert snap['counters']['serving.batch.expired']\n")
        assert lint({"raft_tpu/serving/obs.py": LIB,
                     "tests/test_x.py": test}) == []

    def test_unknown_fault_site_flagged(self):
        test = ("def test_x(plan):\n"
                "    plan.at('serving.swop')\n")
        diags = lint({"raft_tpu/serving/obs.py": LIB,
                      "tests/test_x.py": test})
        assert [d.rule for d in diags] == ["registry-consistency"]
        assert "can never fire" in diags[0].message

    def test_synthetic_test_names_skipped(self):
        # names outside the registry's namespace roots are unit-test
        # synthetics, not references to library metrics
        test = ("def test_x(snap, plan):\n"
                "    assert snap['counters']['c'] == 1\n"
                "    assert snap['counters']['work.done'] == 1\n"
                "    plan.at('site.a')\n")
        assert lint({"raft_tpu/serving/obs.py": LIB,
                     "tests/test_x.py": test}) == []

    TRACED = ("def shed(req):\n"
              "    record_event('serving.shed.deadline', tenant=req.t)\n"
              "def submit():\n"
              "    rt = start_request()\n"
              "    rt.span('serving.admission', 0.0, 1.0)\n"
              "def timed():\n"
              "    with stage('serving.cut'):\n"
              "        pass\n")

    def test_known_event_and_span_references_resolve(self):
        test = ("def test_x(flight, rec):\n"
                "    assert flight.events('serving.shed.deadline')\n"
                "    rec.span('serving.admission', 0.0, 1.0)\n")
        assert lint({"raft_tpu/serving/obs.py": self.TRACED,
                     "tests/test_x.py": test},
                    rules=["registry-consistency"]) == []

    def test_typoed_event_filter_flagged(self):
        test = ("def test_x(flight):\n"
                "    assert flight.events('serving.shed.deadlin')\n")
        diags = lint({"raft_tpu/serving/obs.py": self.TRACED,
                      "tests/test_x.py": test},
                     rules=["registry-consistency"])
        assert [d.rule for d in diags] == ["registry-consistency"]
        assert "serving.shed.deadlin" in diags[0].message

    def test_typoed_span_name_flagged(self):
        test = ("def test_x(rec):\n"
                "    rec.span('serving.admision', 0.0, 1.0)\n")
        diags = lint({"raft_tpu/serving/obs.py": self.TRACED,
                      "tests/test_x.py": test},
                     rules=["registry-consistency"])
        assert [d.rule for d in diags] == ["registry-consistency"]
        assert "never appears in a trace" in diags[0].message

    def test_stage_labels_resolve_as_spans(self):
        # stage() mirrors its timing onto the ambient trace, so a span
        # reference under a stage label is legitimate
        test = ("def test_x(rec):\n"
                "    rec.span('serving.cut', 0.0, 1.0)\n")
        assert lint({"raft_tpu/serving/obs.py": self.TRACED,
                     "tests/test_x.py": test},
                    rules=["registry-consistency"]) == []


# ---------------------------------------------------------------------------
# health-transition

class TestHealthTransition:
    def test_silent_state_mutation_flagged(self):
        src = ("def fail(self, s):\n"
               "    self._state[s] = 'FAILED'\n")
        diags = lint({"raft_tpu/distributed/x.py": src},
                     rules=["health-transition"])
        assert [d.rule for d in diags] == ["health-transition"]
        assert diags[0].line == 2
        assert "paired signal" in diags[0].message

    def test_state_mutation_with_record_event_clean(self):
        src = ("from raft_tpu.observability.flight import record_event\n"
               "def fail(self, s):\n"
               "    self._state[s] = 'FAILED'\n"
               "    record_event('distributed.health.failed', shard=s)\n")
        assert lint({"raft_tpu/distributed/x.py": src},
                    rules=["health-transition"]) == []

    def test_emit_helper_counts_as_signal(self):
        # the tracker's one-level indirection: transitions go through
        # the module _emit helper, not a literal record_event call
        src = ("def fail(self, s):\n"
               "    self._state[s] = 'FAILED'\n"
               "    _emit('distributed.health.failed', shard=s)\n")
        assert lint({"raft_tpu/distributed/x.py": src},
                    rules=["health-transition"]) == []

    def test_state_rule_scoped_to_distributed(self):
        src = ("def fail(self, s):\n"
               "    self._state[s] = 'FAILED'\n")
        assert lint({"raft_tpu/serving/x.py": src},
                    rules=["health-transition"]) == []
        assert lint({"raft_tpu/neighbors/x.py": src},
                    rules=["health-transition"]) == []

    def test_non_state_assignment_clean(self):
        src = ("def note(self, s):\n"
               "    self._strikes[s] = 0\n")
        assert lint({"raft_tpu/distributed/x.py": src},
                    rules=["health-transition"]) == []

    def test_unbumped_successor_placement_flagged(self):
        # reading .generation off an existing placement = deriving a
        # successor; recomputing without generation= skips the bump
        src = ("def recover(index, sizes):\n"
               "    g = index.placement.generation\n"
               "    return compute_placement(sizes, 8)\n")
        diags = lint({"raft_tpu/distributed/x.py": src},
                     rules=["health-transition"])
        assert [d.rule for d in diags] == ["health-transition"]
        assert "generation" in diags[0].message

    def test_bumped_successor_placement_clean(self):
        src = ("def recover(index, sizes):\n"
               "    g = index.placement.generation\n"
               "    return compute_placement(sizes, 8, generation=g + 1)\n")
        assert lint({"raft_tpu/distributed/x.py": src},
                    rules=["health-transition"]) == []

    def test_fresh_placement_exempt(self):
        # no predecessor generation read -> a fresh placement (the
        # shard_by_list path), no bump owed
        src = ("def place(sizes):\n"
               "    return compute_placement(sizes, 8)\n")
        assert lint({"raft_tpu/distributed/x.py": src},
                    rules=["health-transition"]) == []

    def test_placement_rule_covers_serving(self):
        src = ("def rebalance(index, sizes):\n"
               "    g = index.placement.generation\n"
               "    return compute_placement(sizes, 8)\n")
        diags = lint({"raft_tpu/serving/x.py": src},
                     rules=["health-transition"])
        assert [d.rule for d in diags] == ["health-transition"]

    # -- rule 3 (PR 18): load-score mutations go through the tracker --

    def test_adhoc_load_score_write_flagged(self):
        src = ("def tune(self, s):\n"
               "    self._load_score_rows[s] = 0.0\n")
        diags = lint({"raft_tpu/distributed/x.py": src},
                     rules=["health-transition"])
        assert [d.rule for d in diags] == ["health-transition"]
        assert "tracker seam" in diags[0].message

    def test_load_score_rule_covers_serving(self):
        src = ("def tune(self, s):\n"
               "    self.load_scores = self.load_scores * 0.5\n")
        diags = lint({"raft_tpu/serving/x.py": src},
                     rules=["health-transition"])
        assert [d.rule for d in diags] == ["health-transition"]

    def test_load_score_write_through_tracker_clean(self):
        src = ("def fold(self, planned):\n"
               "    self._load_score_rows = 0.7 * self._load_score_rows\n"
               "    self.tracker.note_overload(1, 2.0)\n")
        assert lint({"raft_tpu/distributed/x.py": src},
                    rules=["health-transition"]) == []

    def test_load_score_write_with_emit_clean(self):
        src = ("def fold(self, planned):\n"
               "    self._load_score_rows = planned\n"
               "    _emit('distributed.replica_choice', scores=planned)\n")
        assert lint({"raft_tpu/distributed/x.py": src},
                    rules=["health-transition"]) == []

    def test_load_score_declaration_exempt(self):
        # an ANNOTATED assignment is a declaration (the policy's
        # __init__ zero-init), not a mutation — mirrors the state rule
        src = ("import numpy as np\n"
               "def __init__(self, n):\n"
               "    self._load_score_rows: np.ndarray = np.zeros(n)\n")
        assert lint({"raft_tpu/distributed/x.py": src},
                    rules=["health-transition"]) == []

    def test_load_score_rule_outside_scope_clean(self):
        src = ("def tune(self, s):\n"
               "    self._load_score_rows[s] = 0.0\n")
        assert lint({"raft_tpu/neighbors/x.py": src},
                    rules=["health-transition"]) == []


# ---------------------------------------------------------------------------
# host-sync

class TestHostSync:
    def test_device_coercion_in_hot_fn_flagged(self):
        src = ("import jax.numpy as jnp\n"
               "def search(q):\n"
               "    return int(jnp.max(q))\n")
        diags = lint({"raft_tpu/serving/x.py": src}, rules=["host-sync"])
        assert [d.rule for d in diags] == ["host-sync"]
        assert diags[0].line == 3

    def test_tainted_name_readback_flagged(self):
        # the sync hides behind an assignment: d came off the device
        src = ("import numpy as np\n"
               "import jax.numpy as jnp\n"
               "def _dispatch(q):\n"
               "    d = jnp.sqrt(q)\n"
               "    return np.asarray(d)\n")
        diags = lint({"raft_tpu/serving/x.py": src}, rules=["host-sync"])
        assert [d.rule for d in diags] == ["host-sync"]
        assert diags[0].line == 5

    def test_block_until_ready_flagged(self):
        src = ("def submit(x):\n"
               "    x.block_until_ready()\n"
               "    return x\n")
        diags = lint({"raft_tpu/distributed/x.py": src},
                     rules=["host-sync"])
        assert [d.rule for d in diags] == ["host-sync"]

    def test_shape_metadata_coercion_clean(self):
        # array METADATA is host-resident; int(x.shape[0]) never syncs
        src = ("import jax.numpy as jnp\n"
               "def search(q):\n"
               "    arr = jnp.asarray(q)\n"
               "    n = int(arr.shape[0])\n"
               "    return jnp.zeros((n, arr.ndim))\n")
        assert lint({"raft_tpu/serving/x.py": src},
                    rules=["host-sync"]) == []

    def test_reassignment_clears_taint(self):
        src = ("import numpy as np\n"
               "import jax.numpy as jnp\n"
               "def search(q):\n"
               "    d = jnp.sqrt(q)\n"
               "    d = np.zeros(4)\n"
               "    return float(d[0])\n")
        assert lint({"raft_tpu/serving/x.py": src},
                    rules=["host-sync"]) == []

    def test_scope_and_hot_fn_gating(self):
        src = ("import jax.numpy as jnp\n"
               "def helper(q):\n"
               "    return int(jnp.max(q))\n")
        # cold function inside the scope: clean
        assert lint({"raft_tpu/serving/x.py": src},
                    rules=["host-sync"]) == []
        hot = src.replace("def helper", "def search")
        # hot name outside the serving/distributed scope: clean
        assert lint({"raft_tpu/neighbors/x.py": hot},
                    rules=["host-sync"]) == []

    def test_reasoned_suppression_counted(self):
        # the design contract: every surviving sync point carries an
        # inline reason, so `grep 'disable=host-sync'` enumerates them
        src = ("import jax.numpy as jnp\n"
               "def search(q):\n"
               "    # graftlint: disable=host-sync -- documented readback\n"
               "    return int(jnp.max(q))\n")
        diags, n_sup = run_passes(
            Project.from_sources({"raft_tpu/serving/x.py": src}),
            rules=["host-sync"])
        assert diags == [] and n_sup == 1


# ---------------------------------------------------------------------------
# suppressions

class TestSuppression:
    BAD = "def f(ids):\n    return ids == -1{}\n"

    def test_named_suppression_honored_and_counted(self):
        src = self.BAD.format(
            "  # graftlint: disable=mask-seam -- post-clamp public ids")
        diags, n = run_passes(
            Project.from_sources({"raft_tpu/neighbors/x.py": src}))
        assert diags == [] and n == 1

    def test_bare_disable_suppresses_any_rule(self):
        src = self.BAD.format("  # graftlint: disable")
        diags, n = run_passes(
            Project.from_sources({"raft_tpu/neighbors/x.py": src}))
        assert diags == [] and n == 1

    def test_wrong_rule_name_does_not_suppress(self):
        src = self.BAD.format("  # graftlint: disable=bare-sleep")
        diags, _ = run_passes(
            Project.from_sources({"raft_tpu/neighbors/x.py": src}))
        assert [d.rule for d in diags] == ["mask-seam"]

    def test_comment_only_line_covers_next_line(self):
        src = ("def f(ids):\n"
               "    # graftlint: disable=mask-seam -- reason\n"
               "    return ids == -1\n")
        diags, n = run_passes(
            Project.from_sources({"raft_tpu/neighbors/x.py": src}))
        assert diags == [] and n == 1


# ---------------------------------------------------------------------------
# live tree + generated registry

class TestLiveTree:
    def test_live_tree_is_violation_free(self):
        # the tripwire: the shipped tree must stay clean.  When this
        # fails, either fix the flagged site or suppress it with a
        # reasoned comment (docs/api.md, "Static analysis").
        project = load_project()
        diags, _ = run_passes(project)
        assert diags == [], "\n".join(str(d) for d in diags)

    def test_registry_reflects_live_definitions(self):
        reg = build_registry(load_project())
        d = reg.as_dict()
        # direct literals
        assert "integrity.boundary.checks" in d["counters"]
        assert "xla.compiles" in d["counters"]
        # one-level indirection through the _count(name) helper
        assert "serving.admitted" in d["counters"]
        # fault site defined through the _entry(site, ...) wrapper
        assert "distributed.ann.search" in d["fault_sites"]
        assert "rebalance.swap" in d["fault_sites"]
        # health lifecycle: the tracker's literal-named _emit sites and
        # the readmission fault sites (PR 17)
        for name in ("distributed.health.suspect",
                     "distributed.health.failed",
                     "distributed.health.catch_up",
                     "distributed.health.readmitted",
                     "distributed.health.readmit_blocked",
                     "distributed.health.recovered",
                     "distributed.hedged_reads"):
            assert reg.resolves_metric(name), name
        assert "distributed.catch_up" in d["fault_sites"]
        assert "distributed.swap" in d["fault_sites"]
        # f-string dynamic names register as prefixes
        assert "comms." in d["prefixes"]["counter"]
        assert reg.resolves_metric("comms.allreduce.calls")
        assert not reg.resolves_metric("serving.admited")
        assert "integrity.health_check" in d["stages"]
        # the streaming-ingest surface (PR 13): counters, the
        # visibility histogram, fault sites, and flight events all
        # registered from their literal call sites
        for name in ("serving.ingest.appended", "serving.ingest.acked",
                     "serving.ingest.replayed", "serving.ingest.folds",
                     "serving.ingest.truncations"):
            assert name in d["counters"], name
        assert "serving.ingest.visibility" in d["histograms"]
        for site in ("ingest.append", "ingest.fsync", "ingest.apply",
                     "ingest.fold", "ingest.truncate"):
            assert site in d["fault_sites"], site
        assert "serving.ingest.fold" in d["events"]
        assert "serving.ingest.replay" in d["events"]
        assert "serving.ingest.backpressure" in d["events"]
        assert "serving.ingest.fold" in d["stages"]
        # fold-trigger attribution counters (round 19, satellite)
        assert "serving.ingest.fold_trigger.rows" in d["counters"]
        assert "serving.ingest.fold_trigger.lag" in d["counters"]
        # the distributed ingest surface (round 19): per-shard WAL
        # counters, the write-path kill-matrix fault sites, and the
        # quorum/catch-up flight events, all from literal call sites
        for name in ("serving.ingest.dist.appended",
                     "serving.ingest.dist.acked",
                     "serving.ingest.dist.replayed",
                     "serving.ingest.dist.folds",
                     "serving.ingest.dist.unavailable",
                     "serving.ingest.dist.write_error"):
            assert name in d["counters"], name
        for site in ("ingest.dist.route", "ingest.dist.append",
                     "ingest.dist.ack", "ingest.dist.replicate",
                     "ingest.dist.fold", "ingest.dist.catch_up"):
            assert site in d["fault_sites"], site
        for name in ("serving.ingest.dist.unavailable",
                     "serving.ingest.dist.write_error",
                     "serving.ingest.dist.replay",
                     "serving.ingest.dist.catch_up",
                     "serving.ingest.dist.fold"):
            assert name in d["events"], name
        assert "serving.ingest.dist.fold" in d["stages"]
        # trace spans (serving.request registers through the
        # start_request parameter default) and flight anomaly events
        assert "serving.request" in d["spans"]
        assert "serving.exec" in d["spans"]
        assert "serving.shed.deadline" in d["events"]
        assert "distributed.degraded_search" in d["events"]
        assert "ivf_pq.group_overflow" in d["events"]
        # stage labels double as span names
        assert reg.resolves_span("serving.latency.total") or \
            reg.resolves_span("ivf_pq.search.scan")
        assert not reg.resolves_event("serving.shed.deadlin")

    def test_rule_catalogue_complete(self):
        assert {"recompile-hazard", "generation-discipline", "mask-seam",
                "boundary-guard", "raw-perf-counter", "bare-sleep",
                "registry-consistency", "staging-ring",
                "scratch-budget", "admission-seam"} <= set(rule_docs())


# ---------------------------------------------------------------------------
# CLI

def _cli(*args, cwd=ROOT):
    return subprocess.run(
        [sys.executable, "-m", "scripts.graftlint", *args],
        cwd=str(cwd), capture_output=True, text=True)


class TestCli:
    def test_json_report_on_clean_tree(self):
        out = _cli("--json")
        assert out.returncode == 0, out.stdout + out.stderr
        report = json.loads(out.stdout)
        assert report["diagnostics"] == []
        assert "fault_sites" in report["registry"]
        assert "mask-seam" in report["rules"]

    def test_violations_fail_with_file_line_rule(self, tmp_path):
        pkg = tmp_path / "raft_tpu" / "neighbors"
        pkg.mkdir(parents=True)
        (pkg / "bad.py").write_text("def f(ids):\n    return ids == -1\n")
        out = _cli("--root", str(tmp_path), "--rules", "mask-seam")
        assert out.returncode == 1
        assert "raft_tpu/neighbors/bad.py:2: mask-seam:" in out.stdout

    def test_unknown_rule_is_a_usage_error(self):
        out = _cli("--rules", "no-such-rule")
        assert out.returncode == 2

    def test_list_rules(self):
        out = _cli("--list-rules")
        assert out.returncode == 0
        assert "mask-seam" in out.stdout
        assert "registry-consistency" in out.stdout
