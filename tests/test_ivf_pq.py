"""IVF-PQ tests — recall-based per the reference's ANN pattern
(cpp/test/neighbors/ann_ivf_pq.cuh; ground truth from naive brute force,
``eval_neighbours(min_recall)`` assertions), plus refine composition and
serialization round-trip.
"""

import io

import jax.numpy as jnp
import numpy as np
import pytest

from raft_tpu.distance.types import DistanceType
from raft_tpu.neighbors import ivf_pq, refine
from raft_tpu.random import make_blobs


def naive_knn(db, q, k):
    d = ((q[:, None, :] - db[None, :, :]) ** 2).sum(-1)
    idx = np.argsort(d, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(d, idx, axis=1), idx


def recall(found, truth):
    hits = sum(len(set(f) & set(t)) for f, t in zip(found, truth))
    return hits / truth.size


@pytest.fixture(scope="module")
def dataset():
    X, _ = make_blobs(4000, 32, n_clusters=64, cluster_std=1.0, seed=5)
    return np.asarray(X[:3800]), np.asarray(X[3800:3850])


class TestIvfPq:
    def test_build_shapes(self, res, dataset):
        db, _ = dataset
        params = ivf_pq.IndexParams(n_lists=32, pq_dim=8, pq_bits=8,
                                    kmeans_n_iters=10)
        index = ivf_pq.build(res, params, db)
        assert index.n_lists == 32
        assert index.pq_dim == 8
        assert index.pq_book_size == 256
        assert index.codebooks.shape == (8, 256, index.rot_dim // 8)
        assert index.size == db.shape[0]
        ids = np.asarray(index.list_indices)
        valid = ids[ids >= 0]
        assert sorted(valid.tolist()) == list(range(db.shape[0]))

    def test_search_recall(self, res, dataset):
        db, q = dataset
        params = ivf_pq.IndexParams(n_lists=32, pq_dim=16, pq_bits=8,
                                    kmeans_n_iters=10)
        index = ivf_pq.build(res, params, db)
        d, i = ivf_pq.search(res, ivf_pq.SearchParams(n_probes=16),
                             index, q, 10)
        _, ti = naive_knn(db, q, 10)
        # PQ-compressed distances: recall margin as the reference's
        # low-precision configs (ann_ivf_pq tests allow low_precision_tol)
        assert recall(np.asarray(i), ti) > 0.7

    def test_search_with_refine(self, res, dataset):
        db, q = dataset
        params = ivf_pq.IndexParams(n_lists=32, pq_dim=8, pq_bits=8,
                                    kmeans_n_iters=10)
        index = ivf_pq.build(res, params, db)
        # 4x oversample then exact re-rank — the CAGRA-build composition
        d_raw, i_raw = ivf_pq.search(res, ivf_pq.SearchParams(n_probes=16),
                                     index, q, 10)
        _, i0 = ivf_pq.search(res, ivf_pq.SearchParams(n_probes=16),
                              index, q, 40)
        d, i = refine(res, db, q, i0, 10, metric=DistanceType.L2Expanded)
        _, ti = naive_knn(db, q, 10)
        r_refined = recall(np.asarray(i), ti)
        r_raw = recall(np.asarray(i_raw), ti)
        # refinement must not hurt, and lands decent absolute recall
        assert r_refined >= r_raw
        assert r_refined > 0.75

    def test_bf16_lut(self, res, dataset):
        db, q = dataset
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=8, kmeans_n_iters=5)
        index = ivf_pq.build(res, params, db)
        d32, i32 = ivf_pq.search(res, ivf_pq.SearchParams(n_probes=8),
                                 index, q, 10)
        dbf, ibf = ivf_pq.search(
            res, ivf_pq.SearchParams(n_probes=8, lut_dtype=jnp.bfloat16),
            index, q, 10)
        # bf16 LUT stays close to fp32 results
        assert recall(np.asarray(ibf), np.asarray(i32)) > 0.85

    def test_per_cluster_codebooks(self, res, dataset):
        db, q = dataset
        # pq_dim = dim (1 dim/subspace) + exhaustive probes: quantization
        # is the only loss, so recall must be high — a 0.9 floor instead
        # of the old loose 0.4 smoke check
        params = ivf_pq.IndexParams(
            n_lists=16, pq_dim=32, kmeans_n_iters=10,
            codebook_kind=ivf_pq.CodebookKind.PER_CLUSTER)
        index = ivf_pq.build(res, params, db)
        assert index.codebooks.shape[0] == 16
        d, i = ivf_pq.search(res, ivf_pq.SearchParams(n_probes=16),
                             index, q, 10)
        _, ti = naive_knn(db, q, 10)
        assert recall(np.asarray(i), ti) >= 0.9

    def test_extend(self, res, dataset):
        db, q = dataset
        # 1 dim/subspace + exhaustive probes: an index assembled purely
        # by extend() must reach the same high recall a fresh build does
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=32, kmeans_n_iters=10,
                                    add_data_on_build=False)
        index = ivf_pq.build(res, params, db)
        assert index.size == 0
        index = ivf_pq.extend(res, index, db[:2000],
                              jnp.arange(2000, dtype=jnp.int32))
        index = ivf_pq.extend(res, index, db[2000:],
                              jnp.arange(2000, db.shape[0], dtype=jnp.int32))
        assert index.size == db.shape[0]
        _, i = ivf_pq.search(res, ivf_pq.SearchParams(n_probes=16),
                             index, q, 10)
        _, ti = naive_knn(db, q, 10)
        assert recall(np.asarray(i), ti) >= 0.9
        # matches a fresh add_data_on_build build on the same data
        params2 = ivf_pq.IndexParams(n_lists=16, pq_dim=32,
                                     kmeans_n_iters=10)
        idx2 = ivf_pq.build(res, params2, db)
        _, i2 = ivf_pq.search(res, ivf_pq.SearchParams(n_probes=16),
                              idx2, q, 10)
        assert abs(recall(np.asarray(i), ti)
                   - recall(np.asarray(i2), ti)) < 0.1

    def test_grouped_scan_matches_probe_order_scan(self, res, dataset):
        """The list-centric grouped scan must produce the same results as
        the probe-order scan (same quantized distances; differences are
        bf16-accumulation-order level)."""
        db, q = dataset
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=16, kmeans_n_iters=10)
        index = ivf_pq.build(res, params, db)
        from raft_tpu.neighbors import grouped
        probes = ivf_pq._select_clusters(index.centers, index.rotation,
                                         jnp.asarray(q), 8, index.metric)
        n_groups = grouped.round_groups(
            int(grouped.num_groups(probes, index.n_lists)))
        d1, i1 = ivf_pq._search_impl_recon(
            index.centers, index.list_recon, index.list_indices,
            index.rotation, jnp.asarray(q), 10, 8, index.metric)
        d2, i2 = ivf_pq._search_impl_recon_grouped(
            index.centers, index.list_recon, index.list_recon_sq,
            index.list_indices, index.rotation, jnp.asarray(q), probes,
            10, index.metric, n_groups, 16)
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                                   rtol=1e-2, atol=1e-2)
        overlap = np.mean([len(set(a) & set(b)) / 10
                           for a, b in zip(np.asarray(i1), np.asarray(i2))])
        assert overlap > 0.95

    def test_pallas_group_scan_matches_xla_scan(self, res):
        """The fused Pallas group-scan kernel (interpret mode on CPU) must
        agree with the XLA grouped scan."""
        from raft_tpu.neighbors import grouped
        rng = np.random.default_rng(3)
        db = rng.normal(size=(2000, 128)).astype(np.float32)
        q = rng.normal(size=(32, 128)).astype(np.float32)
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=16, kmeans_n_iters=5)
        index = ivf_pq.build(res, params, db)
        assert index.rot_dim % 128 == 0 and index.capacity % 16 == 0
        probes = ivf_pq._select_clusters(index.centers, index.rotation,
                                         jnp.asarray(q), 8, index.metric)
        n_groups = grouped.round_groups(
            int(grouped.num_groups(probes, index.n_lists)))
        args = (index.centers, index.list_recon, index.list_recon_sq,
                index.list_indices, index.rotation, jnp.asarray(q), probes,
                10, index.metric, n_groups, 16)
        d1, i1 = ivf_pq._search_impl_recon_grouped(*args)
        d2, i2 = ivf_pq._search_impl_recon_grouped(
            *args, use_pallas=True, pallas_interpret=True)
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                                   rtol=1e-2, atol=1e-2)
        overlap = np.mean([len(set(a) & set(b)) / 10
                           for a, b in zip(np.asarray(i1), np.asarray(i2))])
        assert overlap > 0.95

    def test_extend_fast_path_updates_recon_cache(self, res, dataset):
        """A small extend must take the O(n_new) append path (capacity
        unchanged) and keep the bf16 reconstruction cache identical to a
        full re-decode of the codes."""
        db, q = dataset
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=16, kmeans_n_iters=10)
        index = ivf_pq.build(res, params, db[:3000])
        assert index.list_recon is not None
        cap0 = index.capacity
        index = ivf_pq.extend(res, index, db[3000:3040],
                              jnp.arange(3000, 3040, dtype=jnp.int32))
        assert index.capacity == cap0        # fast path: no repack
        assert index.size == 3040
        full = ivf_pq._decode_lists(index.centers, index.codebooks,
                                    index.list_codes, index.codebook_kind,
                                    index.pq_dim, index.pq_bits)
        valid = np.asarray(index.list_indices) >= 0
        np.testing.assert_array_equal(
            np.asarray(index.list_recon, np.float32)[valid],
            np.asarray(full, np.float32)[valid])
        _, i = ivf_pq.search(res, ivf_pq.SearchParams(n_probes=16),
                             index, q, 10)
        _, ti = naive_knn(db[:3040], q, 10)
        assert recall(np.asarray(i), ti) > 0.6

    def test_rotation_orthonormal(self, res, dataset):
        db, _ = dataset
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=5, kmeans_n_iters=3,
                                    force_random_rotation=True)
        index = ivf_pq.build(res, params, db)
        # dim=32 not divisible by 5 -> rot_dim=35, rotation (32, 35) with
        # orthonormal rows ... R R^T = I_32
        r = np.asarray(index.rotation)
        assert r.shape == (32, 35)
        np.testing.assert_allclose(r @ r.T, np.eye(32), atol=1e-4)

    def test_serialize_roundtrip(self, res, dataset):
        db, q = dataset
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=8, kmeans_n_iters=3)
        index = ivf_pq.build(res, params, db)
        buf = io.BytesIO()
        ivf_pq.serialize(res, buf, index)
        buf.seek(0)
        index2 = ivf_pq.deserialize(res, buf)
        d1, i1 = ivf_pq.search(res, ivf_pq.SearchParams(n_probes=4),
                               index, q, 5)
        d2, i2 = ivf_pq.search(res, ivf_pq.SearchParams(n_probes=4),
                               index2, q, 5)
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i2))
        np.testing.assert_allclose(np.asarray(d1), np.asarray(d2),
                                   rtol=1e-5)

    def test_recon_path_matches_lut_path(self, res, dataset):
        """The bf16 reconstruction scan computes the same quantized distance
        as the LUT formulation — indices should agree except for bf16
        rounding flips near ties."""
        db, q = dataset
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=8, kmeans_n_iters=5)
        index = ivf_pq.build(res, params, db)
        assert index.list_recon is not None
        assert index.list_recon.dtype == jnp.bfloat16
        k = 10
        d_r, i_r = ivf_pq.search(
            res, ivf_pq.SearchParams(n_probes=8), index, q, k)
        d_l, i_l = ivf_pq.search(
            res, ivf_pq.SearchParams(n_probes=8, use_reconstruction=False),
            index, q, k)
        i_r, i_l = np.asarray(i_r), np.asarray(i_l)
        overlap = sum(len(set(a) & set(b)) for a, b in zip(i_r, i_l))
        assert overlap / i_l.size >= 0.9
        # bf16 reconstructions round the decoded residuals (~0.4%/element);
        # distances agree coarsely — still far tighter than the reference's
        # fp8 LUT option
        np.testing.assert_allclose(np.asarray(d_r), np.asarray(d_l),
                                   rtol=0.15, atol=0.2)

    def test_pq_bits_4(self, res, dataset):
        db, q = dataset
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=32, pq_bits=4,
                                    kmeans_n_iters=10)
        index = ivf_pq.build(res, params, db)
        assert index.pq_book_size == 16
        # bit-packed codes (ivf_pq_codepacking.cuh parity): pq_bits=4
        # stores HALF the bytes of the one-byte-per-subdim layout
        assert index.code_width == 16
        assert index.pq_dim == 32
        d, i = ivf_pq.search(res, ivf_pq.SearchParams(n_probes=16),
                             index, q, 10)
        _, ti = naive_knn(db, q, 10)
        assert recall(np.asarray(i), ti) > 0.5
        # both search formulations agree on the packed codes
        _, i_lut = ivf_pq.search(res, ivf_pq.SearchParams(
            n_probes=16, use_reconstruction=False), index, q, 10)
        overlap = np.mean([len(set(a) & set(b)) / len(a)
                           for a, b in zip(np.asarray(i),
                                           np.asarray(i_lut))])
        assert overlap >= 0.9

    @pytest.mark.parametrize("pq_bits", [4, 5, 6, 7, 8])
    def test_code_packing_roundtrip(self, pq_bits):
        rng = np.random.default_rng(pq_bits)
        codes = rng.integers(0, 1 << pq_bits,
                             size=(37, 24)).astype(np.uint8)
        packed = ivf_pq._pack_codes(jnp.asarray(codes), pq_bits)
        assert packed.shape == (37, ivf_pq.packed_code_width(24, pq_bits))
        out = ivf_pq._unpack_codes(packed, 24, pq_bits)
        np.testing.assert_array_equal(np.asarray(out), codes)


@pytest.fixture(scope="module")
def scan_index(dataset):
    """One small built index per pq_bits, with every scan cache attached,
    plus the recon-grouped reference results — shared across the
    code-scan parity tests (building dominates their runtime)."""
    from raft_tpu import DeviceResources
    from raft_tpu.neighbors import grouped

    res = DeviceResources(seed=42)
    db, q = dataset
    out = {}
    for pq_bits in (8, 4):
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=8, pq_bits=pq_bits,
                                    kmeans_n_iters=5)
        index = ivf_pq.build(res, params, db)
        probes = ivf_pq._select_clusters(index.centers, index.rotation,
                                         jnp.asarray(q), 8, index.metric)
        ng = grouped.round_groups(
            int(grouped.num_groups(probes, index.n_lists)))
        index = ivf_pq._with_code_lanes(index)
        index = ivf_pq._with_recon8(index)
        rd, ri = ivf_pq._search_impl_recon_grouped(
            index.centers, index.list_recon, index.list_recon_sq,
            index.list_indices, index.rotation, jnp.asarray(q), probes,
            10, index.metric, ng, 64)
        out[pq_bits] = (index, probes, ng, np.asarray(rd), np.asarray(ri))
    return jnp.asarray(q), out


def _overlap(a, b, k=10):
    return np.mean([len(set(x) & set(y)) / k for x, y in zip(a, b)])


class TestCodeScan:
    """Compact-code scan parity (ops/pq_code_scan_pallas, interpret mode
    on CPU): the in-kernel unpack + one-hot codebook decode must
    reproduce the bf16 recon cache's distances bit-for-bit-close."""

    @pytest.mark.parametrize("pq_bits", [8, 4])
    @pytest.mark.parametrize("packed", [False, True])
    def test_codes_matches_recon(self, scan_index, pq_bits, packed):
        q, built = scan_index
        index, probes, ng, rd, ri = built[pq_bits]
        cd, ci = ivf_pq._search_impl_codes_grouped(
            index.centers, index.codebooks, index.list_code_lanes,
            index.list_code_rsq, index.list_indices, index.rotation,
            q, probes, 10, 0, index.metric, ng, index.pq_bits,
            packed=packed, pallas_interpret=True)
        cd, ci = np.asarray(cd), np.asarray(ci)
        assert _overlap(ci, ri) > 0.95
        if not packed:
            np.testing.assert_allclose(cd, rd, rtol=1e-2, atol=1e-2)

    @pytest.mark.parametrize("use_pallas,packed",
                             [(False, False), (True, False), (True, True)])
    def test_recon8_matches_recon(self, scan_index, use_pallas, packed):
        q, built = scan_index
        index, probes, ng, rd, ri = built[8]
        d8, i8 = ivf_pq._search_impl_recon8_grouped(
            index.centers, index.list_recon_i8, index.list_recon_scale,
            index.list_recon_i8_sq, index.list_indices, index.rotation,
            q, probes, 10, 0, index.metric, ng, 64,
            use_pallas=use_pallas, packed=packed, pallas_interpret=True)
        # int8 quantization shifts distances; top-k is nearly preserved
        assert _overlap(np.asarray(i8), ri) > 0.9

    def test_recon8_pallas_matches_xla(self, scan_index):
        """The Pallas dequant kernel and the XLA fallback compute the
        identical quantized distance."""
        q, built = scan_index
        index, probes, ng, _, _ = built[8]
        args = (index.centers, index.list_recon_i8, index.list_recon_scale,
                index.list_recon_i8_sq, index.list_indices, index.rotation,
                q, probes, 10, 0, index.metric, ng, 64)
        dx, ix = ivf_pq._search_impl_recon8_grouped(*args)
        dp, ip = ivf_pq._search_impl_recon8_grouped(
            *args, use_pallas=True, pallas_interpret=True)
        np.testing.assert_allclose(np.asarray(dx), np.asarray(dp),
                                   rtol=1e-2, atol=1e-2)
        assert _overlap(np.asarray(ip), np.asarray(ix)) > 0.95

    def test_per_probe_topk_matches_recon_at_same_kt(self, scan_index):
        """kt parity must compare same-kt paths: the codes kernel's
        per-probe top-kt keep-set equals the recon path's at the same
        kt (kt vs full-k is NOT an identity — a query whose true top-k
        concentrates in one probe legitimately loses candidates)."""
        q, built = scan_index
        index, probes, ng, _, _ = built[8]
        _, ki = ivf_pq._search_impl_codes_grouped(
            index.centers, index.codebooks, index.list_code_lanes,
            index.list_code_rsq, index.list_indices, index.rotation,
            q, probes, 10, 4, index.metric, ng, index.pq_bits,
            pallas_interpret=True)
        _, oi = ivf_pq._search_impl_recon_grouped(
            index.centers, index.list_recon, index.list_recon_sq,
            index.list_indices, index.rotation, q, probes, 10,
            index.metric, ng, 64, kt=4)
        assert _overlap(np.asarray(ki), np.asarray(oi)) > 0.95

    def test_rsq_from_codes_matches_recon_sq(self, scan_index):
        """Per-row squared norms derived straight from the packed codes
        (codes mode carries no recon cache) equal the cache-derived
        norms."""
        _, built = scan_index
        for pq_bits in (8, 4):
            index = built[pq_bits][0]
            rsq = ivf_pq._rsq_from_codes(index.codebooks, index.list_codes,
                                         index.pq_dim, index.pq_bits)
            err = np.max(np.abs(np.asarray(rsq)
                                - np.asarray(index.list_recon_sq)))
            assert err < 1e-3, err

    def test_codes_mode_recall_matches_recon_mode(self, res, dataset):
        """Public search(): scan_mode="codes" must land the same recall
        as scan_mode="recon" at identical operating points (on CPU the
        codes mode runs its portable LUT fallback — the contract is the
        same either way)."""
        db, q = dataset
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=32,
                                    kmeans_n_iters=10)
        index = ivf_pq.build(res, params, db)
        _, ti = naive_knn(db, q, 10)
        recalls = {}
        for mode in ("recon", "codes", "recon8"):
            sp = ivf_pq.SearchParams(n_probes=16, scan_mode=mode)
            _, i = ivf_pq.search(res, sp, index, q, 10)
            recalls[mode] = recall(np.asarray(i), ti)
        assert recalls["recon"] >= 0.9
        assert abs(recalls["codes"] - recalls["recon"]) < 0.05, recalls
        assert abs(recalls["recon8"] - recalls["recon"]) < 0.05, recalls


class TestFusedScan:
    """Fused in-kernel top-k parity (interpret mode on CPU): the
    per-query accumulator kernels must reproduce the scatter + select
    reference path at matched kt — same candidates kept per (query,
    probe), same final ids and distances."""

    @pytest.mark.parametrize("kt", [0, 4])
    def test_fused_codes_matches_reference_at_same_kt(self, scan_index,
                                                      kt):
        q, built = scan_index
        index, probes, ng, _, _ = built[8]
        rd, ri = ivf_pq._search_impl_codes_grouped(
            index.centers, index.codebooks, index.list_code_lanes,
            index.list_code_rsq, index.list_indices, index.rotation,
            q, probes, 10, kt, index.metric, ng, index.pq_bits,
            pallas_interpret=True)
        fd, fi = ivf_pq._search_impl_fused_codes_grouped(
            index.centers, index.codebooks, index.list_code_lanes,
            index.list_code_rsq, index.list_indices, index.rotation,
            q, probes, 10, kt, index.metric, ng, index.pq_bits,
            pallas_interpret=True)
        rd, ri = np.asarray(rd), np.asarray(ri)
        fd, fi = np.asarray(fd), np.asarray(fi)
        assert _overlap(fi, ri) > 0.95
        fin = np.isfinite(rd) & np.isfinite(fd)
        np.testing.assert_array_equal(np.isfinite(rd), np.isfinite(fd))
        np.testing.assert_allclose(fd[fin], rd[fin], rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("pq_bits", [8, 4])
    def test_fused_recon_matches_reference(self, scan_index, pq_bits):
        q, built = scan_index
        index, probes, ng, rd, ri = built[pq_bits]
        fd, fi = ivf_pq._search_impl_fused_recon_grouped(
            index.centers, index.list_recon, index.list_recon_sq,
            index.list_indices, index.rotation, q, probes, 10,
            0, index.metric, ng, pallas_interpret=True)
        fd, fi = np.asarray(fd), np.asarray(fi)
        assert _overlap(fi, ri) > 0.95
        fin = np.isfinite(rd) & np.isfinite(fd)
        np.testing.assert_allclose(fd[fin], rd[fin], rtol=1e-4, atol=1e-4)

    # interpreter-mode Pallas at kt=cap+7 dominates this module's wall
    # clock on CPU; the CI fused-tripwire step runs it by node id (no
    # marker filter), keeping it out of the fast tier only.
    @pytest.mark.slow
    def test_fused_kt_exceeds_list_length(self, scan_index):
        """kt past the list capacity clips to cap — every candidate of
        every probed list survives to the merge, so the fused result is
        the exact union top-k."""
        q, built = scan_index
        index, probes, ng, _, _ = built[8]
        cap = index.capacity
        rd, ri = ivf_pq._search_impl_recon_grouped(
            index.centers, index.list_recon, index.list_recon_sq,
            index.list_indices, index.rotation, q, probes, 10,
            index.metric, ng, 64, kt=cap + 7)
        fd, fi = ivf_pq._search_impl_fused_recon_grouped(
            index.centers, index.list_recon, index.list_recon_sq,
            index.list_indices, index.rotation, q, probes, 10,
            cap + 7, index.metric, ng, pallas_interpret=True)
        assert _overlap(np.asarray(fi), np.asarray(ri)) > 0.95
        fin = np.isfinite(np.asarray(rd))
        np.testing.assert_allclose(np.asarray(fd)[fin],
                                   np.asarray(rd)[fin],
                                   rtol=1e-4, atol=1e-4)

    def test_fused_sentinel_rows_stay_masked(self, scan_index):
        """id -1 rows (the integrity mask / tombstone contract) must
        never surface from the fused kernel: zapped candidates drop out
        and exhausted ranks keep id -1 / worst (+inf) distance."""
        q, built = scan_index
        index, probes, ng, _, _ = built[8]
        zapped = jnp.asarray(
            np.where(np.arange(index.capacity)[None, :] % 2 == 0,
                     np.asarray(index.list_indices), -1))
        fd, fi = ivf_pq._search_impl_fused_recon_grouped(
            index.centers, index.list_recon, index.list_recon_sq,
            zapped, index.rotation, q, probes, 10, 0, index.metric,
            ng, pallas_interpret=True)
        fd, fi = np.asarray(fd), np.asarray(fi)
        surviving = set(np.asarray(zapped)[np.asarray(zapped) >= 0])
        assert all(int(i) in surviving for i in fi[fi >= 0])
        # exhausted ranks: -1 id paired with +inf distance, never a
        # finite distance with a stale id
        np.testing.assert_array_equal(fi == -1, ~np.isfinite(fd))

    def test_fused_mode_recall_matches_recon_mode(self, res, dataset):
        """Public search(): scan_mode="fused" lands the same recall as
        "recon" at identical operating points (on CPU it falls back to
        the non-fused backing path — same results either way)."""
        db, q = dataset
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=32,
                                    kmeans_n_iters=10)
        index = ivf_pq.build(res, params, db)
        _, ti = naive_knn(db, q, 10)
        sp_r = ivf_pq.SearchParams(n_probes=16, scan_mode="recon")
        _, i_r = ivf_pq.search(res, sp_r, index, q, 10)
        sp_f = ivf_pq.SearchParams(n_probes=16, scan_mode="fused")
        _, i_f = ivf_pq.search(res, sp_f, index, q, 10)
        r_recon = recall(np.asarray(i_r), ti)
        r_fused = recall(np.asarray(i_f), ti)
        assert r_recon >= 0.9
        assert abs(r_fused - r_recon) < 0.05, (r_fused, r_recon)

    def test_fused_fallback_is_counted(self, res, dataset):
        """The CI tripwire's sensor: every dispatch that asked for the
        fused kernel but ran a fallback must tick
        ivf_pq.search.fused_fallback (on CPU that is every fused/auto
        dispatch — on TPU at the flagship shape the counter must stay
        flat, which bench.py asserts at runtime)."""
        from raft_tpu import observability as obs

        db, q = dataset
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=32,
                                    kmeans_n_iters=2)
        index = ivf_pq.build(res, params, db)
        obs.enable()
        try:
            reg = obs.registry()
            c0 = reg.counter("ivf_pq.search.fused_fallback").value
            r0 = reg.counter(
                "ivf_pq.search.fused_fallback.reason.backend").value
            sp = ivf_pq.SearchParams(n_probes=8, scan_mode="fused")
            ivf_pq.search(res, sp, index, q, 10)
            c1 = reg.counter("ivf_pq.search.fused_fallback").value
            r1 = reg.counter(
                "ivf_pq.search.fused_fallback.reason.backend").value
        finally:
            obs.disable()
        assert c1 == c0 + 1
        # round-14 reason codes: off-TPU misses attribute to "backend"
        assert r1 == r0 + 1

    def test_fused_supported_at_flagship_shape(self):
        """Static tripwire: the fused kernels must accept the flagship
        bench geometry (1M x 128, 4096 lists, pq_dim 64, kt 16, batch
        5000).  If a VMEM-budget or gate edit regresses this,
        scan_mode=auto would silently fall off the fused kernel at the
        headline operating point — fail HERE, not in the QPS number."""
        from raft_tpu.ops import pq_code_scan_pallas as pcs
        from raft_tpu.ops import pq_group_scan_pallas as pqp

        cap = -(-int(1_000_000 / 4096 * 1.35) // 32) * 32
        assert pcs.supported_fused_codes(True, True, cap, 128, 16, 10,
                                         5000, 64, 8)
        assert pqp.supported_fused(True, cap, 128, 16, 10, 5000)


@pytest.fixture(scope="module")
def ring_case():
    """Tiny synthetic geometry for staging-ring semantics: 7 lists of
    capacity 32 with the last 5 rows of every list tombstoned (id -1),
    9 queries x 3 probes.  n_groups is deliberately coprime with every
    tested W, so each sweep crosses a partial final window, and k=256
    exceeds the live candidate pool, so the accumulator tail stays
    all-sentinel through entire windows."""
    from raft_tpu.neighbors import grouped

    rng = np.random.default_rng(0)
    n_lists, cap, rot, nq, n_probes = 7, 32, 128, 9, 3
    probes = np.stack([rng.choice(n_lists, size=n_probes, replace=False)
                       for _ in range(nq)]).astype(np.int32)
    n_groups, _ = grouped.group_capacity(nq, n_probes, n_lists)
    gl, sp = grouped.build_groups(jnp.asarray(probes), n_lists, n_groups)
    qrot = rng.standard_normal((nq, rot)).astype(np.float32)
    centers = rng.standard_normal((n_lists, rot)).astype(np.float32)
    recon = jnp.asarray(
        rng.standard_normal((n_lists, cap, rot)).astype(np.float32),
        jnp.bfloat16)
    rsq = jnp.sum(jnp.asarray(recon, jnp.float32) ** 2, axis=-1)
    ids = rng.integers(0, 1 << 20, size=(n_lists, cap)).astype(np.int32)
    ids[:, -5:] = -1
    return dict(gl=gl, sp=sp, qrot=jnp.asarray(qrot),
                centers=jnp.asarray(centers), recon=recon, rsq=rsq,
                ids=jnp.asarray(ids), ids_np=ids, kt=8,
                n_probes=n_probes, nq=nq, P=nq * n_probes)


class TestWindowedMerge:
    """Round-14 windowed fused-scan merge: a VMEM staging ring defers
    the (k x k+kt) merge to every W-th grid step.  The contract is
    bit-identity with the round-7 per-step merge (W=1): VALUES bit-equal
    at every rank, IDS bit-equal at every live rank (exhausted ranks
    all carry the sentinel value, so their relative id order is
    unspecified; the epilogue maps every such rank to +inf / -1)."""

    def _run(self, c, k, W):
        from raft_tpu.ops import pq_group_scan_pallas as pqp

        v, i = pqp.grouped_l2_scan_fused(
            c["gl"], c["sp"], c["qrot"], c["centers"], c["recon"],
            c["rsq"], c["ids"], c["kt"], k, c["n_probes"],
            interpret=True, merge_window=W)
        return np.asarray(v), np.asarray(i)

    def test_bit_identity_across_windows(self, ring_case):
        from raft_tpu.ops import pq_group_scan_pallas as pqp

        base_v, base_i = self._run(ring_case, 10, 1)
        live = base_v < pqp._ACC_WORST / 2
        for W in (2, 3, 8):            # none divide n_groups
            v, i = self._run(ring_case, 10, W)
            np.testing.assert_array_equal(base_v, v)
            np.testing.assert_array_equal(base_i[live], i[live])

    @pytest.mark.parametrize("k", [128, 256])
    def test_large_k_windowed_matches_reference(self, ring_case, k):
        """k past the unrolled-merge ceiling takes the fori-loop merge.
        Windowed runs must agree with each other bit-for-bit and with
        the non-fused kernel + host-side sort at matched kt."""
        from raft_tpu.neighbors import grouped
        from raft_tpu.ops import pq_group_scan_pallas as pqp

        c = ring_case
        av, ai = self._run(c, k, 2)
        bv, bi = self._run(c, k, 5)
        live = av < pqp._ACC_WORST / 2
        np.testing.assert_array_equal(av, bv)
        np.testing.assert_array_equal(ai[live], bi[live])
        nv, ni = pqp.grouped_l2_scan(
            c["gl"], c["sp"], c["qrot"], c["centers"], c["recon"],
            c["rsq"], c["ids"], c["kt"], c["n_probes"], interpret=True)
        outd, outi = grouped.scatter_packed(nv, ni, c["sp"], c["P"],
                                            True)
        outd, outi = np.asarray(outd), np.asarray(outi)
        npb = c["n_probes"]
        for q in range(c["nq"]):
            cd = outd[q * npb:(q + 1) * npb].reshape(-1)
            ci = outi[q * npb:(q + 1) * npb].reshape(-1)
            fin = np.isfinite(cd)
            order = np.argsort(cd[fin], kind="stable")
            ref_d, ref_i = cd[fin][order][:k], ci[fin][order][:k]
            good = av[:k, q] < pqp._ACC_WORST / 2
            np.testing.assert_array_equal(av[:k, q][good],
                                          ref_d[:good.sum()])
            np.testing.assert_array_equal(ai[:k, q][good],
                                          ref_i[:good.sum()])
            assert good.sum() == min(k, fin.sum())

    def test_tombstones_never_surface_through_staging_ring(self,
                                                           ring_case):
        """The last 5 rows of every list carry id -1 (the tombstone /
        integrity-mask contract): the ring's sentinel fill must never
        resurrect them at any W, and exhausted ranks must come back as
        sentinel-value / -1 pairs — never a live value with a stale
        id left over from a previous window."""
        from raft_tpu.ops import pq_group_scan_pallas as pqp

        ids_np = ring_case["ids_np"]
        alive = set(ids_np[ids_np >= 0].tolist())
        for W in (1, 4):
            v, i = self._run(ring_case, 64, W)
            live = v < pqp._ACC_WORST / 2
            # the raw kernel output predates the epilogue, so only live
            # ranks carry a contract: a real (non-tombstoned) id, never
            # the -1 ring fill
            assert (i[live] >= 0).all()
            assert all(int(x) in alive for x in i[live])

    def test_fused_codes_windowed_large_k(self, scan_index):
        """Codes-kernel staging ring at k=128: windowed merge is
        bit-identical to the per-step merge and lands the same
        candidates as the non-fused codes path at matched kt."""
        q, built = scan_index
        index, probes, ng, _, _ = built[8]
        args = (index.centers, index.codebooks, index.list_code_lanes,
                index.list_code_rsq, index.list_indices, index.rotation,
                q, probes, 128, 4, index.metric, ng, index.pq_bits)
        f1d, f1i = ivf_pq._search_impl_fused_codes_grouped(
            *args, pallas_interpret=True, merge_window=1)
        f4d, f4i = ivf_pq._search_impl_fused_codes_grouped(
            *args, pallas_interpret=True, merge_window=4)
        f1d, f1i = np.asarray(f1d), np.asarray(f1i)
        f4d, f4i = np.asarray(f4d), np.asarray(f4i)
        np.testing.assert_array_equal(f1d, f4d)
        fin = np.isfinite(f1d)
        np.testing.assert_array_equal(f1i[fin], f4i[fin])
        rd, ri = ivf_pq._search_impl_codes_grouped(
            *args, pallas_interpret=True)
        rd, ri = np.asarray(rd), np.asarray(ri)
        both = fin & np.isfinite(rd)
        np.testing.assert_allclose(f4d[both], rd[both], rtol=1e-4,
                                   atol=1e-4)
        # k=128 exceeds the kt=4 candidate pool (8 probes x 4), so both
        # paths keep EVERY candidate: the finite id sets match exactly
        for r in range(f4i.shape[0]):
            assert (set(f4i[r][f4i[r] >= 0].tolist())
                    == set(ri[r][ri[r] >= 0].tolist()))

    def test_xla_twin_windowed_scatter_matches(self, scan_index):
        """grouped.scan_and_scatter's merge_window (the AOT export's
        XLA twin of the staging ring) defers the scatter to one pass
        per W blocks; the scatter is idempotent over disjoint slots, so
        every W must reproduce the unwindowed result exactly."""
        q, built = scan_index
        index, probes, ng, rd, ri = built[8]
        for W in (1, 3):
            wd_, wi_ = ivf_pq._search_impl_recon_grouped(
                index.centers, index.list_recon, index.list_recon_sq,
                index.list_indices, index.rotation, q, probes, 10,
                index.metric, ng, 64, merge_window=W)
            np.testing.assert_array_equal(np.asarray(wd_), rd)
            np.testing.assert_array_equal(np.asarray(wi_), ri)


class TestListDataHelpers:
    """Public list-data helpers (reference: ivf_pq_helpers.cuh)."""

    @pytest.mark.parametrize("pq_bits", [4, 8])
    def test_unpack_pack_roundtrip(self, res, dataset, pq_bits):
        from raft_tpu.neighbors import ivf_pq_helpers as h

        db, _ = dataset
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=16, pq_bits=pq_bits,
                                    kmeans_n_iters=5)
        index = ivf_pq.build(res, params, db)
        label = int(np.argmax(np.asarray(index.list_sizes)))
        size = int(index.list_sizes[label])
        codes = np.asarray(h.unpack_list_data(res, index, label))
        assert codes.shape == (size, index.pq_dim)
        assert codes.max() < (1 << pq_bits)
        # windowed read agrees with the full read
        win = np.asarray(h.unpack_list_data(res, index, label,
                                            offset=2, n_rows=3))
        np.testing.assert_array_equal(win, codes[2:5])
        # pack the same codes back: index unchanged (incl. recon cache)
        before = np.asarray(index.list_recon[label, :size])
        index = h.pack_list_data(res, index, label, codes)
        np.testing.assert_array_equal(
            np.asarray(h.unpack_list_data(res, index, label)), codes)
        np.testing.assert_array_equal(
            np.asarray(index.list_recon[label, :size]), before)

    def test_pack_edits_search_results(self, res, dataset):
        from raft_tpu.neighbors import ivf_pq_helpers as h

        db, q = dataset
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=16,
                                    kmeans_n_iters=5)
        index = ivf_pq.build(res, params, db)
        label = int(np.argmax(np.asarray(index.list_sizes)))
        size = int(index.list_sizes[label])
        # overwrite every code in the list with code 0: recon cache must
        # follow (searches see the edit), per the reference's contract
        zeros = np.zeros((size, index.pq_dim), np.uint8)
        index = h.pack_list_data(res, index, label, zeros)
        np.testing.assert_array_equal(
            np.asarray(h.unpack_list_data(res, index, label)), zeros)
        got = np.asarray(index.list_recon[label, :size])
        want = np.asarray(ivf_pq._decode_rows(
            index.codebooks, jnp.asarray(zeros),
            jnp.full((size,), label, jnp.int32), index.codebook_kind))
        np.testing.assert_array_equal(got, want)

    def test_reconstruct_list_data(self, res, dataset):
        from raft_tpu.neighbors import ivf_pq_helpers as h

        db, _ = dataset
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=16,
                                    kmeans_n_iters=5)
        index = ivf_pq.build(res, params, db)
        label = int(np.argmax(np.asarray(index.list_sizes)))
        size = int(index.list_sizes[label])
        rec = np.asarray(h.reconstruct_list_data(res, index, label))
        assert rec.shape == (size, db.shape[1])
        ids = np.asarray(index.list_indices[label, :size])
        orig = db[ids]
        # PQ reconstruction error is bounded well below the data scale
        rel = (np.linalg.norm(rec - orig, axis=1)
               / np.maximum(np.linalg.norm(orig, axis=1), 1e-6))
        assert float(np.median(rel)) < 0.5


class TestGroupCapacity:
    """Round 10: shape-static group capacity — the grouped dispatch no
    longer syncs a per-batch group count, and a calibrated index's
    tightened capacity is covered by the in-graph overflow fallback."""

    def test_worst_bound_is_exact_and_total(self):
        from raft_tpu.neighbors import grouped
        cap, exact = grouped.group_capacity(16, 8, 32)
        assert exact
        assert cap == -(-16 * 8 // grouped.GROUP) + min(32, 16 * 8)
        # degenerate batch: still a valid (static) dispatch shape
        assert grouped.group_capacity(0, 8, 32) == (1, True)
        # calibrated capacity never exceeds the worst bound
        t, e = grouped.group_capacity(16, 8, 32, est=0.9)
        assert t <= cap and (e or t < cap)

    def test_probe_overlap_order_above_int32_key_range(self):
        """Regression (round 10): at n_lists=65536 the old fused sort
        key r0*(n_lists+1)+r1 wraps int32 — the two-pass stable lexsort
        must match numpy's lexsort exactly."""
        from raft_tpu.neighbors import grouped
        n_lists = 65536
        assert (n_lists + 1) ** 2 > np.iinfo(np.int32).max
        rng = np.random.default_rng(3)
        probes = rng.integers(0, n_lists, size=(512, 4), dtype=np.int32)
        order = np.asarray(grouped.probe_overlap_order(
            jnp.asarray(probes), n_lists))
        r0 = np.minimum(probes[:, 0], n_lists)
        r1 = np.minimum(probes[:, 1], n_lists)
        np.testing.assert_array_equal(order, np.lexsort((r1, r0)))
        # and the small-n_lists fast path agrees with the same model
        small = rng.integers(0, 64, size=(256, 4), dtype=np.int32)
        got = np.asarray(grouped.probe_overlap_order(jnp.asarray(small),
                                                     64))
        np.testing.assert_array_equal(
            got, np.lexsort((np.minimum(small[:, 1], 64),
                             np.minimum(small[:, 0], 64))))

    def test_executable_reuse_across_group_counts(self, res, dataset):
        """Two batches at the SAME shape with DIFFERENT true group
        counts must share one executable — the capacity, not the count,
        is the compiled shape (the round-10 churn fix)."""
        from raft_tpu import observability as obs
        from raft_tpu.neighbors import grouped
        db, q = dataset
        params = ivf_pq.IndexParams(n_lists=32, pq_dim=8,
                                    kmeans_n_iters=5,
                                    cache_reconstructions=True)
        index = ivf_pq.build(res, params, db)
        sp = ivf_pq.SearchParams(n_probes=8, scan_mode="recon")
        narrow = np.tile(np.asarray(q[:1]), (16, 1))
        spread = np.asarray(q[:16])
        pn = ivf_pq._select_clusters(index.centers, index.rotation,
                                     jnp.asarray(narrow), 8, index.metric)
        ps = ivf_pq._select_clusters(index.centers, index.rotation,
                                     jnp.asarray(spread), 8, index.metric)
        assert (int(grouped.num_groups(pn, 32))
                < int(grouped.num_groups(ps, 32)))
        with obs.collecting():
            ivf_pq.search(res, sp, index, narrow, 10)    # warm the shape
            c0 = obs.registry().counter("xla.compiles").value
            ivf_pq.search(res, sp, index, spread, 10)
            ivf_pq.search(res, sp, index, narrow, 10)
            c1 = obs.registry().counter("xla.compiles").value
        assert c1 == c0, f"{c1 - c0} recompiles across group-count change"
        # the churn mechanism itself is gone: no per-batch group cache
        assert not hasattr(grouped, "cached_groups")
        assert not hasattr(grouped, "commit_groups")

    def test_calibrated_overflow_redispatch_is_exact(self, res, dataset,
                                                     monkeypatch):
        """Calibrate on a narrow batch, then search a wider one: the
        overflow counter must tick and the worst-bound re-dispatch must
        return exactly the uncalibrated answer."""
        from raft_tpu import observability as obs
        from raft_tpu.neighbors import grouped
        db, q = dataset
        params = ivf_pq.IndexParams(n_lists=32, pq_dim=8,
                                    kmeans_n_iters=5,
                                    cache_reconstructions=True)
        index = ivf_pq.build(res, params, db)
        # drop the compile-cache quantum so this test-sized index can
        # exceed a tightened capacity (at the default 256 the rounded
        # capacity clamps to the worst bound at this scale)
        monkeypatch.setattr(grouped, "_GROUP_ROUND", 1)
        sp = ivf_pq.SearchParams(n_probes=8, scan_mode="recon")
        spread = np.asarray(q)                 # 50 blob queries
        d0, i0 = ivf_pq.search(res, sp, index, spread, 10)
        narrow = np.tile(np.asarray(q[:1]), (len(spread), 1))
        est = ivf_pq.calibrate_group_capacity(res, index, narrow, 8)
        assert 0.0 < est < 1.0
        cap, exact = grouped.group_capacity(len(spread), 8, 32,
                                            est=index.group_est)
        worst, _ = grouped.group_capacity(len(spread), 8, 32)
        assert not exact and cap < worst, (cap, worst)
        with obs.collecting():
            d1, i1 = ivf_pq.search(res, sp, index, spread, 10)
            n_over = obs.registry().counter(
                "ivf_pq.search.group_overflow").value
        assert n_over >= 1, "wide batch must trip the overflow gate"
        np.testing.assert_array_equal(np.asarray(i1), np.asarray(i0))
        np.testing.assert_array_equal(np.asarray(d1), np.asarray(d0))
        # repeated calibration ratchets: a wider batch raises the
        # estimate, a narrower one never lowers it
        est2 = ivf_pq.calibrate_group_capacity(res, index, spread, 8)
        assert est2 >= est
        assert ivf_pq.calibrate_group_capacity(res, index, narrow, 8) == est2

    def test_group_est_rides_serialization_v4(self, res, dataset):
        from raft_tpu.neighbors import grouped
        db, q = dataset
        params = ivf_pq.IndexParams(n_lists=16, pq_dim=8,
                                    kmeans_n_iters=4,
                                    cache_reconstructions=True)
        index = ivf_pq.build(res, params, db)
        ivf_pq.calibrate_group_capacity(res, index, np.asarray(q), 8)
        assert index.group_est > 0.0
        buf = io.BytesIO()
        ivf_pq.serialize(res, buf, index)
        buf.seek(0)
        back = ivf_pq.deserialize(res, buf)
        assert back.group_est == index.group_est
