"""Stats tests — compare against numpy/scipy/sklearn-style references on
small random data (the reference's compute-vs-reference pattern, SURVEY.md §4;
reference tests: cpp/test/stats/*.cu).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from raft_tpu import stats

RNG = np.random.default_rng(0)


class TestMoments:
    def test_mean_stddev_minmax(self):
        x = RNG.normal(size=(200, 8)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(stats.mean(x)), x.mean(0),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(stats.stddev(x)),
                                   x.std(0, ddof=1), rtol=1e-4)
        mn, mx = stats.minmax(x)
        np.testing.assert_allclose(np.asarray(mn), x.min(0))
        np.testing.assert_allclose(np.asarray(mx), x.max(0))

    def test_meanvar_rowwise(self):
        x = RNG.normal(size=(50, 30)).astype(np.float32)
        mu, var = stats.meanvar(x, rowwise=True)
        np.testing.assert_allclose(np.asarray(mu), x.mean(1), rtol=1e-5,
                                   atol=1e-5)
        np.testing.assert_allclose(np.asarray(var), x.var(1, ddof=1),
                                   rtol=1e-4)

    def test_mean_center_add_roundtrip(self):
        x = RNG.normal(size=(40, 6)).astype(np.float32)
        c = stats.mean_center(x)
        np.testing.assert_allclose(np.asarray(c).mean(0), 0, atol=1e-5)
        back = stats.mean_add(c, stats.mean(x))
        np.testing.assert_allclose(np.asarray(back), x, rtol=1e-5, atol=1e-5)

    def test_cov(self):
        x = RNG.normal(size=(300, 5)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(stats.cov(x)),
                                   np.cov(x, rowvar=False), rtol=1e-3,
                                   atol=1e-4)

    def test_histogram(self):
        x = RNG.uniform(0, 10, size=(500, 3)).astype(np.float32)
        h = np.asarray(stats.histogram(x, 10, lower=0.0, upper=10.0))
        for c in range(3):
            ref, _ = np.histogram(x[:, c], bins=10, range=(0, 10))
            np.testing.assert_array_equal(h[:, c], ref)

    def test_weighted_mean(self):
        x = RNG.normal(size=(20, 4)).astype(np.float32)
        w = RNG.uniform(0.1, 1, size=4).astype(np.float32)
        out = np.asarray(stats.row_weighted_mean(x, w))
        ref = (x * w[None, :]).sum(1) / w.sum()
        np.testing.assert_allclose(out, ref, rtol=1e-5)


class TestClusterMetrics:
    def test_contingency_and_ari_perfect(self):
        y = RNG.integers(0, 4, 100)
        ari = stats.adjusted_rand_index(y, y, n_classes_true=4,
                                       n_classes_pred=4)
        np.testing.assert_allclose(float(ari), 1.0, atol=1e-6)

    def test_ari_vs_sklearn_formula(self):
        y1 = np.asarray([0, 0, 1, 1, 2, 2, 2])
        y2 = np.asarray([0, 0, 1, 2, 2, 2, 2])
        try:
            from sklearn.metrics import adjusted_rand_score
            ref = adjusted_rand_score(y1, y2)
        except ImportError:
            ref = 0.6470588235  # precomputed
        ari = stats.adjusted_rand_index(y1, y2, n_classes_true=3,
                                       n_classes_pred=3)
        np.testing.assert_allclose(float(ari), ref, atol=1e-5)

    def test_rand_index(self):
        y1 = np.asarray([0, 0, 1, 1])
        y2 = np.asarray([0, 0, 1, 2])
        # pairs: (01)+ (23)- agree: (01) same/same, (23) same/diff ->
        # agreements: all pairs except (2,3): 5/6
        ri = stats.rand_index(y1, y2)
        np.testing.assert_allclose(float(ri), 5 / 6, atol=1e-6)

    def test_entropy_uniform(self):
        y = np.repeat(np.arange(4), 25)
        e = stats.entropy(y, n_classes=4)
        np.testing.assert_allclose(float(e), np.log(4), atol=1e-5)

    def test_v_measure_homogeneity_completeness(self):
        y_true = np.asarray([0, 0, 1, 1])
        y_pred = np.asarray([0, 0, 1, 1])
        for f in (stats.homogeneity_score, stats.completeness_score,
                  stats.v_measure):
            v = f(y_true, y_pred, n_classes_true=2, n_classes_pred=2)
            np.testing.assert_allclose(float(v), 1.0, atol=1e-5)

    def test_mutual_info_independent(self):
        y1 = np.asarray([0, 0, 1, 1] * 25)
        y2 = np.asarray([0, 1, 0, 1] * 25)
        mi = stats.mutual_info_score(y1, y2, n_classes_true=2,
                                     n_classes_pred=2)
        np.testing.assert_allclose(float(mi), 0.0, atol=1e-5)

    def test_silhouette_vs_sklearn(self):
        x = RNG.normal(size=(60, 4)).astype(np.float32)
        x[:30] += 5.0
        labels = np.asarray([0] * 30 + [1] * 30)
        from raft_tpu.distance.types import DistanceType
        s = stats.silhouette_score(x, labels, n_clusters=2,
                                   metric=DistanceType.L2SqrtExpanded)
        try:
            from sklearn.metrics import silhouette_score as sk
            ref = sk(x, labels)
            np.testing.assert_allclose(float(s), ref, atol=1e-3)
        except ImportError:
            assert float(s) > 0.5

    def test_silhouette_batched_matches(self):
        x = RNG.normal(size=(50, 4)).astype(np.float32)
        labels = RNG.integers(0, 3, 50)
        full = stats.silhouette_score(x, labels, n_clusters=3)
        batched = stats.silhouette_score(x, labels, n_clusters=3, chunk=16)
        np.testing.assert_allclose(float(full), float(batched), atol=1e-5)

    def test_dispersion(self):
        centroids = np.asarray([[0.0, 0.0], [2.0, 0.0]], np.float32)
        sizes = np.asarray([2, 2], np.int32)
        # global centroid (1,0); disp = sqrt(2*1 + 2*1) = 2
        d = stats.dispersion(centroids, sizes)
        np.testing.assert_allclose(float(d), 2.0, atol=1e-6)


class TestRegressionMetrics:
    def test_accuracy(self):
        a = np.asarray([1, 2, 3, 4])
        b = np.asarray([1, 2, 0, 4])
        np.testing.assert_allclose(float(stats.accuracy(a, b)), 0.75)

    def test_r2(self):
        y = RNG.normal(size=100).astype(np.float32)
        np.testing.assert_allclose(float(stats.r2_score(y, y)), 1.0,
                                   atol=1e-6)
        y_hat = y + RNG.normal(size=100).astype(np.float32) * 0.1
        r2 = float(stats.r2_score(y, y_hat))
        assert 0.9 < r2 <= 1.0

    def test_regression_metrics(self):
        y = np.asarray([1.0, 2.0, 3.0], np.float32)
        p = np.asarray([1.5, 2.0, 2.0], np.float32)
        mae, mse, medae = stats.regression_metrics(p, y)
        np.testing.assert_allclose(float(mae), 0.5, atol=1e-6)
        np.testing.assert_allclose(float(mse), (0.25 + 0 + 1) / 3, atol=1e-6)
        np.testing.assert_allclose(float(medae), 0.5, atol=1e-6)

    def test_information_criterion(self):
        ll = np.asarray([-100.0, -50.0], np.float32)
        aic = stats.information_criterion_batched(ll, stats.IC_Type.AIC, 3,
                                                 1000)
        np.testing.assert_allclose(np.asarray(aic), [206.0, 106.0])
        bic = stats.information_criterion_batched(ll, stats.IC_Type.BIC, 3,
                                                 1000)
        np.testing.assert_allclose(np.asarray(bic),
                                   -2 * ll + 3 * np.log(1000), rtol=1e-6)

    def test_kl_divergence(self):
        p = np.asarray([0.5, 0.5], np.float32)
        q = np.asarray([0.9, 0.1], np.float32)
        ref = (0.5 * np.log(0.5 / 0.9) + 0.5 * np.log(0.5 / 0.1))
        np.testing.assert_allclose(float(stats.kl_divergence(p, q)), ref,
                                   rtol=1e-4)

    def test_trustworthiness_identity(self):
        x = RNG.normal(size=(50, 8)).astype(np.float32)
        t = stats.trustworthiness_score(x, x, 5)
        np.testing.assert_allclose(float(t), 1.0, atol=1e-5)

    def test_trustworthiness_random_embedding(self):
        x = RNG.normal(size=(50, 8)).astype(np.float32)
        emb = RNG.normal(size=(50, 2)).astype(np.float32)
        t = float(stats.trustworthiness_score(x, emb, 5))
        assert t < 0.8
