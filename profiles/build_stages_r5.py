"""Warm per-stage breakdown of the clustered CAGRA build at 1M
(mirrors _build_knn_graph_clustered with forced syncs between stages;
second run reported so compiles are excluded)."""

import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def main():
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/raft_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    import jax.numpy as jnp
    from raft_tpu import DeviceResources
    from raft_tpu.cluster import kmeans_balanced
    from raft_tpu.distance.types import DistanceType
    from raft_tpu.neighbors import cagra

    n, dim, latent = 1_000_000, 128, 16
    rng = np.random.default_rng(0)
    Z = rng.normal(size=(n, latent)).astype(np.float32)
    A = rng.normal(size=(latent, dim)).astype(np.float32) / np.sqrt(latent)
    X = (Z @ A).astype(np.float32)
    X += 0.05 * rng.normal(size=X.shape).astype(np.float32)
    db = jnp.asarray(X)
    db.block_until_ready()
    res = DeviceResources(seed=0)
    p = cagra.IndexParams(graph_degree=64)
    kg = 129
    xf = db.astype(jnp.float32)

    for run in range(2):
        t_all = time.perf_counter()
        t0 = time.perf_counter()
        n_lists = max(min(n // 64, 4 * int(np.sqrt(n))), 8)
        bal = kmeans_balanced.KMeansBalancedParams(
            n_iters=10, metric=DistanceType.L2Expanded)
        n_train = min(n, max(n_lists * 8, max(65536, n // 10)))
        trainset = xf[::max(n // n_train, 1)][:n_train]
        centers = kmeans_balanced.fit(res, bal, trainset, n_lists)
        labels = kmeans_balanced.predict(res, bal, xf, centers)
        sizes = jax.ops.segment_sum(jnp.ones(n, jnp.int32), labels,
                                    num_segments=n_lists)
        cap = max(-(-int(jnp.max(sizes)) // 8) * 8, 8)
        t_km = time.perf_counter() - t0

        t0 = time.perf_counter()
        C = max(int(p.build_refine_rate * kg), kg)
        pdim, vecs = cagra._build_pdim(db, p.metric, kg, C)
        np.asarray(vecs[0, 0])
        t_calib = time.perf_counter() - t0

        t0 = time.perf_counter()
        proj = (vecs[:, dim - pdim:] if pdim < dim
                else jnp.eye(dim, dtype=jnp.float32))
        P_proj, P_sq, P_id = cagra._build_layout(
            xf, xf @ proj, labels, n_lists, cap)
        nbrs = cagra._center_neighbors(centers, 33, False)
        np.asarray(P_id[0, 0])
        t_layout = time.perf_counter() - t0

        mean = max(n / n_lists, 1.0)
        t = min(n_lists, max(p.build_n_probes,
                             -(-p.build_candidates // int(mean))))
        nbrs = cagra._center_neighbors(centers, t, False)
        t0 = time.perf_counter()
        LB = max(1, min(8, (256 << 20) // max(cap * t * cap * 4, 1)))
        CH = cagra._SCAN_LISTS_PER_DISPATCH
        n_pad = -(-n_lists // (LB * CH)) * (LB * CH) \
            if n_lists > LB * CH else -(-n_lists // LB) * LB
        ids = np.minimum(np.arange(n_pad, dtype=np.int32), n_lists - 1)
        knn = jnp.full((n, kg), -1, jnp.int32)
        for s in range(0, n_pad, LB * CH):
            cid = jnp.asarray(ids[s:s + LB * CH])
            out_c = cagra._scan_chunk(P_proj, P_sq, P_id, nbrs, cid,
                                      cap, kg, False, LB)
            rows = P_id[cid].reshape(-1)
            rows = jnp.where(rows >= 0, rows, n)
            knn = knn.at[rows].set(out_c.reshape(-1, kg), mode="drop")
        np.asarray(knn[0, 0])
        t_scan = time.perf_counter() - t0

        t0 = time.perf_counter()
        rev = cagra._reverse_edges(knn, n, kg)
        knn, knn_d = cagra._merge_refine_chunked(xf, knn, rev, kg, False,
                                                 with_d=True)
        np.asarray(knn[0, 0])
        t_rev = time.perf_counter() - t0

        walk_times = []
        for r in range(2):
            t0 = time.perf_counter()
            knn, knn_d = cagra._graph_refine_round(res, db, knn, kg,
                                                   p.metric, pdim, 8,
                                                   knn_d=knn_d)
            np.asarray(knn[0, 0])
            walk_times.append(round(time.perf_counter() - t0, 1))

        t0 = time.perf_counter()
        ids2 = jnp.arange(n, dtype=knn.dtype)[:, None]
        order = jnp.argsort(knn == ids2, axis=1, stable=True)
        knn_ns = jnp.take_along_axis(knn, order, axis=1)[:, :128]
        graph = cagra.prune(res, knn_ns.astype(jnp.int32), 64)
        np.asarray(graph[0, 0])
        t_prune = time.perf_counter() - t0

        print(json.dumps({
            "run": run, "pdim": pdim, "t": t, "cap": cap, "LB": LB,
            "kmeans_s": round(t_km, 1), "calib_s": round(t_calib, 1),
            "layout_s": round(t_layout, 1), "scan_s": round(t_scan, 1),
            "revmerge_s": round(t_rev, 1), "walk_s": walk_times,
            "prune_s": round(t_prune, 1),
            "total_s": round(time.perf_counter() - t_all, 1)}),
            flush=True)


if __name__ == "__main__":
    main()
