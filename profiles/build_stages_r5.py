"""Warm per-stage breakdown of the clustered CAGRA build at 1M.

Round-5 version hand-replicated _build_knn_graph_clustered with forced
syncs between stages; now the build itself is instrumented
(raft_tpu.observability stages fence at every stage boundary when
collection is on), so this just runs the REAL build twice under
``obs.collecting()`` and prints each build's attached stage report —
second run reported warm so compiles are excluded (run 0 also carries
the ``xla.*`` compile timers captured via jax.monitoring).
"""

import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def main():
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/raft_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    import jax.numpy as jnp
    from raft_tpu import DeviceResources
    from raft_tpu import observability as obs
    from raft_tpu.neighbors import cagra

    n, dim, latent = 1_000_000, 128, 16
    rng = np.random.default_rng(0)
    Z = rng.normal(size=(n, latent)).astype(np.float32)
    A = rng.normal(size=(latent, dim)).astype(np.float32) / np.sqrt(latent)
    X = (Z @ A).astype(np.float32)
    X += 0.05 * rng.normal(size=X.shape).astype(np.float32)
    db = jnp.asarray(X)
    db.block_until_ready()
    res = DeviceResources(seed=0)
    p = cagra.IndexParams(graph_degree=64)

    for run in range(2):
        obs.reset()
        t_all = time.perf_counter()
        with obs.collecting():
            index = cagra.build(res, p, db)
            np.asarray(index.graph[0, 0])
        total_s = time.perf_counter() - t_all
        rep = obs.build_report(index)
        snap = obs.snapshot()
        print(json.dumps({
            "run": run,
            "total_s": round(total_s, 1),
            "stages": {name: {"count": t["count"],
                              "total_s": round(t["total_s"], 1)}
                       for name, t in sorted(rep["stages"].items())},
            "counters": rep["counters"],
            # run 0 only: XLA compile time captured via jax.monitoring
            "xla_compile_s": round(sum(
                t["total_s"] for name, t in snap["timers"].items()
                if name.startswith("xla.")), 1),
        }), flush=True)


if __name__ == "__main__":
    main()
