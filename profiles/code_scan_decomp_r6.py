"""Round-6 A/B decomposition: where the IVF-PQ scan's HBM bytes go.

Two parts:

- ``--model`` (runs anywhere, CPU included): the static per-candidate-row
  HBM traffic of each scan mode at the bench shape — the acceptance
  number for the compact-code path (codes bytes/row must be < half the
  recon path's) — plus the per-batch totals implied by the measured
  group count, and (round 14) the merge-side decomposition: selection
  rows swept per grid step by the per-step merge vs the windowed merge
  at the modeled optimal W, for k in {10, 64, 128, 256}.
- on-chip timing (default): kernel-only A/B of recon vs codes vs recon8
  at matched (n_probes, kt), isolating the scan from coarse select and
  refine, plus (round 14) a windowed-vs-per-step fused A/B at the
  flagship shape; --trace captures a profiler trace of all three.

Run on the real chip:  python profiles/code_scan_decomp_r6.py [--trace]
Traffic model only:    python profiles/code_scan_decomp_r6.py --model
"""

import sys
import time

import numpy as np


def traffic_model(cap, rot, pq_dim, pq_bits, n_groups, group=128):
    from raft_tpu.neighbors import grouped

    per_row = grouped.scan_traffic(rot, pq_dim, pq_bits)
    print(f"per-candidate-row HBM bytes (rot={rot}, pq_dim={pq_dim}, "
          f"pq_bits={pq_bits}):")
    for mode in ("recon", "recon8", "codes", "fused"):
        b = per_row[mode]
        ratio = b / per_row["recon"]
        print(f"  {mode:>7}: {b:4d} B/row  ({ratio:.2f}x recon)")
    assert per_row["codes"] < per_row["recon"] / 2, (
        "codes bytes/row must undercut half the recon path's")
    print(f"per-batch scan totals at n_groups={n_groups}, cap={cap} "
          f"(each group streams its list's rows once):")
    for mode in ("recon", "recon8", "codes", "fused"):
        total = n_groups * cap * per_row[mode]
        print(f"  {mode:>7}: {total / 1e9:6.2f} GB")
    return per_row


def output_model(kt, k, nq, n_probes, n_groups, group=128):
    """Round-7 columns: the OUTPUT side of the scan — what the fused
    in-kernel top-k eliminates.  The split path writes a (dist, id) pair
    per kept candidate per (query, probe) pair, then re-reads it through
    scatter + select; fused mode keeps the running top-k in VMEM scratch
    and writes one (k, nq) answer pair for the whole batch."""
    from raft_tpu.neighbors import grouped

    per_pair = grouped.pair_output_traffic(kt)
    n_pairs = nq * n_probes
    split_total = n_pairs * per_pair
    fused_total = 2 * 4 * k * nq            # final (vals, ids), f32
    print(f"extraction/output traffic at kt={kt}, k={k}, nq={nq}, "
          f"n_probes={n_probes}:")
    print(f"  split: {per_pair} B/pair x {n_pairs} pairs = "
          f"{split_total / 1e6:7.1f} MB  (+ scatter/select passes)")
    print(f"  fused: one ({k}, {nq}) answer pair     = "
          f"{fused_total / 1e6:7.1f} MB")
    print(f"  predicted elimination: {split_total / fused_total:6.1f}x "
          "output bytes, extraction stage -> 0 (in-kernel)")
    # round-5 extraction cost model: ~3.3 us per kept candidate per
    # group of pairs — the wall-clock the fused kernel absorbs
    pair_groups = -(-n_pairs // group)
    print(f"  predicted extraction wall-clock absorbed: "
          f"~{3.3e-6 * kt * pair_groups * 1e3:.1f} ms/batch")
    return split_total, fused_total


def merge_model(kt, nq, cap, rot, group=128):
    """Round-14 columns: the MERGE side of the fused scan — the cost the
    windowed staging ring amortizes.  The per-step merge sweeps a
    (k + kt, cols) concat k times every grid step; the windowed merge
    stages W steps with an O(kt) one-hot write and sweeps the
    (k + kt*W, cols) concat only every W-th step, so the amortized
    per-step selection rows drop by ~(k + kt) * W / (k + kt*W).  W is
    the budget model's host-static choice (ops.vmem_budget via
    pq_group_scan_pallas.fused_merge_window) at this shape."""
    from raft_tpu.ops import pq_group_scan_pallas as pqp

    print(f"fused-scan merge decomposition at kt={kt}, nq={nq} "
          f"(stream side: {cap * rot * 2} B recon bytes per group, "
          "for scale):")
    for k in (10, 64, 128, 256):
        per_step = k * (k + kt)
        W = pqp.fused_merge_window(cap, rot, kt, k, nq)
        if W == 0:
            reason = pqp.fused_reject_reason(True, cap, rot, kt, k, nq)
            print(f"  k={k:>3}: fused unsupported ({reason})")
            continue
        # amortized selection rows per grid step + the staging write
        windowed = k * (k + kt * W) / W + 2 * kt
        note = "" if k <= 64 else "  (per-step merge hypothetical: the" \
                                  " unrolled path gates k<=64)"
        print(f"  k={k:>3}: per-step {per_step:6d} rows/step   "
              f"windowed W={W}: {windowed:8.0f} rows/step   "
              f"{per_step / windowed:5.2f}x fewer{note}")


def main():
    import jax

    sys.path.insert(0, ".")
    import bench
    from raft_tpu import DeviceResources
    from raft_tpu.neighbors import grouped, ivf_pq

    model_only = "--model" in sys.argv
    if model_only:
        # bench-shape geometry without building: cap from the mean list
        # occupancy rounded like the list allocator
        n_db, n_lists, pq_dim, pq_bits, rot = 1_000_000, 4096, 64, 8, 128
        cap = -(-int(n_db / n_lists * 1.35) // 32) * 32
        n_groups = 23_000   # measured round-5 magnitude at n_probes=96
        traffic_model(cap, rot, pq_dim, pq_bits, n_groups)
        output_model(kt=4, k=10, nq=5_000, n_probes=96,
                     n_groups=n_groups)
        # round 14: the merge side at the flagship batch and at the
        # large-k operating points the windowed engine unlocks (large k
        # exceeds the flagship's VMEM at nq=5000 — model the serving
        # large-k bucket's batch as well)
        merge_model(kt=16, nq=5_000, cap=cap, rot=rot)
        merge_model(kt=16, nq=1_024, cap=cap, rot=rot)
        return

    bench._setup_jax_cache()
    res = DeviceResources(seed=0)
    db, queries = bench._make_dataset({"n_db": 1_000_000, "dim": 128,
                                       "latent_dim": 16, "noise": 0.05,
                                       "n_queries": 5_000})
    params = ivf_pq.IndexParams(n_lists=4096, pq_dim=64, kmeans_n_iters=20)
    t0 = time.perf_counter()
    index = ivf_pq.build(res, params, db)
    jax.block_until_ready(index.list_codes)
    print("build_s", round(time.perf_counter() - t0, 1))

    n_probes, k, kt = 72, 20, 4
    m = index.metric
    probes = ivf_pq._select_clusters(index.centers, index.rotation,
                                     queries, n_probes, m)
    n_groups = grouped.round_groups(
        int(grouped.num_groups(probes, index.n_lists)))
    cap = index.capacity
    G, rot = grouped.GROUP, index.rot_dim
    block = grouped.block_size(n_groups, G * cap * 8, cap * rot * 2,
                               G * rot * 4)
    print("n_groups", n_groups, "cap", cap)
    traffic_model(cap, rot, index.pq_dim, index.pq_bits, n_groups)

    index = ivf_pq._with_recon(res, index)
    index = ivf_pq._with_code_lanes(index)
    index = ivf_pq._with_recon8(index)
    rot_pad = index.list_recon_i8.shape[2]
    block8 = grouped.block_size(n_groups, G * cap * 8, cap * rot_pad * 3,
                                G * rot_pad * 4)

    def run_recon(kt_):
        return ivf_pq._search_impl_recon_grouped(
            index.centers, index.list_recon, index.list_recon_sq,
            index.list_indices, index.rotation, queries, probes, k, m,
            n_groups, block, use_pallas=True, kt=kt_)[1]

    def run_codes(kt_, packed=False):
        return ivf_pq._search_impl_codes_grouped(
            index.centers, index.codebooks, index.list_code_lanes,
            index.list_code_rsq, index.list_indices, index.rotation,
            queries, probes, k, kt_, m, n_groups, index.pq_bits,
            packed=packed)[1]

    def run_recon8(kt_, packed=False):
        return ivf_pq._search_impl_recon8_grouped(
            index.centers, index.list_recon_i8, index.list_recon_scale,
            index.list_recon_i8_sq, index.list_indices, index.rotation,
            queries, probes, k, kt_, m, n_groups, block8, use_pallas=True,
            packed=packed)[1]

    def run_fused_codes(kt_, mw=1):
        return ivf_pq._search_impl_fused_codes_grouped(
            index.centers, index.codebooks, index.list_code_lanes,
            index.list_code_rsq, index.list_indices, index.rotation,
            queries, probes, k, kt_, m, n_groups, index.pq_bits,
            merge_window=mw)[1]

    def run_fused_recon(kt_, mw=1):
        return ivf_pq._search_impl_fused_recon_grouped(
            index.centers, index.list_recon, index.list_recon_sq,
            index.list_indices, index.rotation, queries, probes, k, kt_,
            m, n_groups, merge_window=mw)[1]

    variants = [
        ("recon      kt=k ", lambda: run_recon(0)),
        (f"recon      kt={kt} ", lambda: run_recon(kt)),
        ("codes      kt=k ", lambda: run_codes(0)),
        (f"codes      kt={kt} ", lambda: run_codes(kt)),
        (f"codes-pk   kt={kt} ", lambda: run_codes(kt, packed=True)),
        ("recon8     kt=k ", lambda: run_recon8(0)),
        (f"recon8     kt={kt} ", lambda: run_recon8(kt)),
        (f"recon8-pk  kt={kt} ", lambda: run_recon8(kt, packed=True)),
        # round-7: scan + top-k in ONE kernel, no extraction stage
        (f"fused-cod  kt={kt} ", lambda: run_fused_codes(kt)),
        (f"fused-rec  kt={kt} ", lambda: run_fused_recon(kt)),
    ]
    # round-14: windowed merge A/B at the flagship shape — same kernels,
    # merge every W-th grid step instead of every step (bit-identical)
    from raft_tpu.ops import pq_code_scan_pallas as pcs_mod
    from raft_tpu.ops import pq_group_scan_pallas as pqp_mod
    w_cod = pcs_mod.fused_codes_merge_window(cap, rot, kt, k,
                                             queries.shape[0],
                                             index.pq_dim, index.pq_bits)
    w_rec = pqp_mod.fused_merge_window(cap, rot, kt, k, queries.shape[0])
    if w_cod > 1:
        variants.append((f"fused-cod  W={w_cod}  ",
                         lambda: run_fused_codes(kt, mw=w_cod)))
    if w_rec > 1:
        variants.append((f"fused-rec  W={w_rec}  ",
                         lambda: run_fused_recon(kt, mw=w_rec)))
    timed = {}
    for name, fn in variants:
        i = fn()
        np.asarray(i)                    # warm
        t0 = time.perf_counter()
        for _ in range(3):
            i = fn()
        np.asarray(i)
        dt = (time.perf_counter() - t0) / 3
        timed[name.strip()] = dt
        print(f"{name}: {dt*1000:7.1f} ms/batch  ({5000/dt:7.0f} qps)")

    # measured extraction-stage elimination: the codes-vs-fused delta at
    # matched kt IS the (extraction + scatter + select) stage the fused
    # kernel absorbed — print it beside the static model's prediction
    split = timed[f"codes      kt={kt}".strip()]
    fused = timed[f"fused-cod  kt={kt}".strip()]
    print(f"measured extraction elimination (codes kt={kt} -> fused): "
          f"{(split - fused) * 1e3:+.1f} ms/batch "
          f"({split / fused:.2f}x)")
    if w_rec > 1:
        w1 = timed[f"fused-rec  kt={kt}".strip()]
        ww = timed[f"fused-rec  W={w_rec}".strip()]
        print(f"measured windowed-merge gain (fused-rec W=1 -> "
              f"W={w_rec}): {(w1 - ww) * 1e3:+.1f} ms/batch "
              f"({w1 / ww:.2f}x)")
    output_model(kt=kt, k=k, nq=queries.shape[0], n_probes=n_probes,
                 n_groups=n_groups)
    merge_model(kt=kt, nq=queries.shape[0], cap=cap, rot=rot)

    if "--trace" in sys.argv:
        with jax.profiler.trace("profiles/code_scan_trace"):
            np.asarray(run_recon(kt))
            np.asarray(run_codes(kt))
            np.asarray(run_recon8(kt))
        print("trace written to profiles/code_scan_trace")


if __name__ == "__main__":
    main()
