"""Stage-by-stage 10M CAGRA build with forced syncs — pinpoints the
OOM stage the fused conf run hides behind async dispatch."""

import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def main():
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/raft_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    import jax.numpy as jnp
    from raft_tpu import DeviceResources
    from raft_tpu.cluster import kmeans_balanced
    from raft_tpu.distance.types import DistanceType
    from raft_tpu.neighbors import cagra

    n, dim, latent = 10_000_000, 96, 16
    rng = np.random.default_rng(0)
    Z = rng.normal(size=(n, latent)).astype(np.float32)
    A = rng.normal(size=(latent, dim)).astype(np.float32) / np.sqrt(latent)
    X = (Z @ A).astype(np.float32)
    X += 0.05 * rng.normal(size=X.shape).astype(np.float32)
    del Z
    db = jnp.asarray(X)
    del X
    db.block_until_ready()
    res = DeviceResources(seed=0)
    p = cagra.IndexParams(graph_degree=32,
                          intermediate_graph_degree=64,
                          build_n_probes=12)
    kg = 65
    xf = db

    def stage(name, fn):
        t0 = time.perf_counter()
        out = fn()
        print(json.dumps({"stage": name,
                          "s": round(time.perf_counter() - t0, 1)}),
              flush=True)
        return out

    n_lists = max(min(n // 64, 4 * int(np.sqrt(n))), 8)
    C = max(int(p.build_refine_rate * kg), kg)
    pdim, vecs = stage("calib", lambda: cagra._build_pdim(
        db, p.metric, kg, C))
    np.asarray(vecs[0, 0])
    print(json.dumps({"pdim": int(pdim)}), flush=True)
    proj = (vecs[:, dim - pdim:] if pdim < dim
            else jnp.eye(dim, dtype=jnp.float32))
    xp32 = xf @ proj
    bal = kmeans_balanced.KMeansBalancedParams(
        n_iters=10, metric=DistanceType.L2Expanded)
    n_train = min(n, max(n_lists * 8, max(65536, n // 10)))
    trainset = xp32[::max(n // n_train, 1)][:n_train]
    centers = stage("kmeans_fit", lambda: jax.block_until_ready(
        kmeans_balanced.fit(res, bal, trainset, n_lists)))
    labels = stage("predict", lambda: jax.block_until_ready(
        kmeans_balanced.predict(res, bal, xp32, centers)))
    sizes = jax.ops.segment_sum(jnp.ones(n, jnp.int32), labels,
                                num_segments=n_lists)
    cap = max(-(-int(jnp.max(sizes)) // 8) * 8, 8)
    print(json.dumps({"n_lists": n_lists, "cap": cap}), flush=True)
    P_proj, P_sq, P_id = stage("layout", lambda: jax.block_until_ready(
        cagra._build_layout(xf, xp32, labels, n_lists, cap)))
    del xp32
    mean = max(n / n_lists, 1.0)
    t = min(n_lists, max(p.build_n_probes,
                         -(-p.build_candidates // int(mean))))
    nbrs = cagra._center_neighbors(centers, t, False)
    print(json.dumps({"t": t}), flush=True)

    LB = max(1, min(8, (256 << 20) // max(cap * t * cap * 4, 1)))
    CH = cagra._SCAN_LISTS_PER_DISPATCH
    n_pad = -(-n_lists // (LB * CH)) * (LB * CH) \
        if n_lists > LB * CH else -(-n_lists // LB) * LB
    ids = np.minimum(np.arange(n_pad, dtype=np.int32), n_lists - 1)

    def scan():
        knn = jnp.full((n, kg), -1, jnp.int32)
        for s in range(0, n_pad, LB * CH):
            cid = jnp.asarray(ids[s:s + LB * CH])
            out_c = cagra._scan_chunk(P_proj, P_sq, P_id, nbrs, cid,
                                      cap, kg, False, LB,
                                      rt=p.build_scan_recall)
            rows = P_id[cid].reshape(-1)
            rows = jnp.where(rows >= 0, rows, n)
            knn = knn.at[rows].set(out_c.reshape(-1, kg), mode="drop")
        return jax.block_until_ready(knn)

    knn = stage("scan", scan)
    del P_proj, P_sq, P_id
    rev = stage("rev_host", lambda: cagra._reverse_edges_auto(
        knn, n, min(kg, 64)))
    knn = stage("rev_merge", lambda: jax.block_until_ready(
        cagra._merge_refine_inplace(db, knn, rev, kg, False)))
    del rev
    for r in range(p.build_walk_rounds):
        knn = stage(f"walk{r}", lambda: jax.block_until_ready(
            cagra._deep_walk_round(db, knn, kg, p.metric, pdim,
                                   p.build_walk_iters)))
    graph = stage("prune", lambda: jax.block_until_ready(
        cagra.prune(res, jnp.take_along_axis(
            knn, jnp.argsort(knn == jnp.arange(n, dtype=knn.dtype)[:, None],
                             axis=1, stable=True), axis=1
        )[:, :64].astype(jnp.int32), 32)))
    print(json.dumps({"graph_shape": list(graph.shape)}), flush=True)


if __name__ == "__main__":
    main()
