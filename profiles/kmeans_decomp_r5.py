"""Round-5 k-means kernel decomposition: isolate matmul / min / argmin /
epilogue shares at tile 2048 so the 80 it/s push targets the real cost.

Variants (cumulative):
  mm        — distance matmul only, write one ip column (no k-reduction)
  mmmin     — + row min over k (dmin output)
  mmargmin  — + argmin (labels), still no epilogue
  full      — + one-hot epilogue matmul + counts (== kmeans_kernel_r5 uw)
"""

import functools
import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir", "/tmp/raft_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import jax.numpy as jnp  # noqa: E402
from jax.experimental import pallas as pl  # noqa: E402

from raft_tpu.ops.kmeans_update_pallas import _round_up  # noqa: E402


def _make_kernel(which):
    def kern(x_ref, c_ref, csq_ref, sums_ref, counts_ref, dmin_ref):
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            sums_ref[...] = jnp.zeros_like(sums_ref)
            counts_ref[...] = jnp.zeros_like(counts_ref)

        x = x_ref[...]
        ip = jax.lax.dot_general(x, c_ref[...], (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        d = csq_ref[...] - 2.0 * ip
        if which == "mm":
            dmin_ref[...] = d[:, :1]
            return
        dmin = jnp.min(d, axis=1, keepdims=True)
        dmin_ref[...] = dmin
        if which == "mmmin":
            return
        labels = jnp.argmin(d, axis=1)
        if which == "mmargmin":
            counts_ref[...] += jnp.sum(labels.astype(jnp.float32)
                                       )[None, None]
            return
        cols = jax.lax.broadcasted_iota(jnp.int32, d.shape, 1)
        onehot = (cols == labels[:, None]).astype(jnp.bfloat16)
        sums_ref[...] += jax.lax.dot_general(
            onehot, x, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        counts_ref[...] += jnp.sum(onehot.astype(jnp.float32), axis=0,
                                   keepdims=True)
    return kern


@functools.partial(jax.jit, static_argnames=("tile", "which"))
def run(x, centroids, tile, which):
    n, dim = x.shape
    k = centroids.shape[0]
    n_pad = _round_up(n, tile)
    k_pad = _round_up(k, 128)
    d_pad = _round_up(dim, 128)
    cf = centroids.astype(jnp.float32)
    c_sq = jnp.sum(cf * cf, axis=1)
    csq_p = jnp.full((1, k_pad), jnp.inf, jnp.float32).at[0, :k].set(c_sq)
    c_p = jnp.zeros((k_pad, d_pad), jnp.bfloat16).at[:k, :dim].set(
        cf.astype(jnp.bfloat16))
    x_p = jnp.zeros((n_pad, d_pad), jnp.bfloat16).at[:n, :dim].set(
        x.astype(jnp.bfloat16))
    sums, counts, dmin = pl.pallas_call(
        _make_kernel(which),
        grid=(n_pad // tile,),
        in_specs=[
            pl.BlockSpec((tile, d_pad), lambda i: (i, 0)),
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, k_pad), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, k_pad), lambda i: (0, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k_pad, d_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, k_pad), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        ],
    )(x_p, c_p, csq_p)
    return sums, counts, dmin


def time_it(fn, reps=10):
    out = fn()
    np.asarray(jax.tree_util.tree_leaves(out)[0])
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    np.asarray(jax.tree_util.tree_leaves(out)[0])
    return (time.perf_counter() - t0) / reps


def main():
    n, dim, k = 1_000_000, 128, 1024
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n, dim)).astype(np.float32))
    c = jnp.asarray(rng.normal(size=(k, dim)).astype(np.float32))
    x.block_until_ready()
    for which in ("mm", "mmmin", "mmargmin", "full"):
        for tile in (2048, 4096):
            try:
                ms = time_it(lambda: run(x, c, tile, which)) * 1e3
                print(json.dumps({"variant": which, "tile": tile,
                                  "ms": round(ms, 2)}), flush=True)
            except Exception as e:
                print(json.dumps({"variant": which, "tile": tile,
                                  "error": str(e)[:120]}), flush=True)


if __name__ == "__main__":
    main()
