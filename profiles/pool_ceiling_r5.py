"""Measure the clustered-build candidate-pool ceiling at 1M: what
fraction of the exact top-kg neighbors live inside the union of the
query's list's top-t neighbor lists, for a sample of queries."""

import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def main():
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/raft_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    import jax.numpy as jnp
    from raft_tpu import DeviceResources
    from raft_tpu.cluster import kmeans_balanced
    from raft_tpu.distance.types import DistanceType
    from raft_tpu.neighbors import brute_force, cagra

    n, dim, latent = 1_000_000, 128, 16
    rng = np.random.default_rng(0)
    Z = rng.normal(size=(n, latent)).astype(np.float32)
    A = rng.normal(size=(latent, dim)).astype(np.float32) / np.sqrt(latent)
    X = (Z @ A).astype(np.float32)
    X += 0.05 * rng.normal(size=X.shape).astype(np.float32)
    db = jnp.asarray(X)
    db.block_until_ready()
    res = DeviceResources(seed=0)
    kg = 129

    n_lists = max(min(n // 64, 4 * int(np.sqrt(n))), 8)
    bal = kmeans_balanced.KMeansBalancedParams(
        n_iters=10, metric=DistanceType.L2Expanded)
    n_train = min(n, max(n_lists * 8, max(65536, n // 10)))
    t0 = time.perf_counter()
    trainset = db[::max(n // n_train, 1)][:n_train]
    centers = kmeans_balanced.fit(res, bal, trainset, n_lists)
    labels = np.asarray(kmeans_balanced.predict(res, bal, db, centers))
    print(json.dumps({"cluster_s": round(time.perf_counter() - t0, 1),
                      "n_lists": n_lists}), flush=True)

    sample = np.arange(0, n, 4001)[:250]
    _, gt = brute_force.knn(res, db, db[sample], kg)
    gt = np.asarray(gt)

    for t in (32, 48, 64, 96):
        nbrs = np.asarray(cagra._center_neighbors(centers, t, False))
        ok = tot = 0
        for qi, g in zip(sample, gt):
            cl = set(nbrs[labels[qi]].tolist())
            ok += sum(labels[j] in cl for j in g)
            tot += len(g)
        print(json.dumps({"t": t, "ceiling": round(ok / tot, 4)}),
              flush=True)


if __name__ == "__main__":
    main()
