"""Measure graph-walk refinement rounds at 1M: graph recall and walk
recall per round count, with stage timings."""

import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def main():
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/raft_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    import jax.numpy as jnp
    from raft_tpu import DeviceResources
    from raft_tpu.neighbors import brute_force, cagra

    n, dim, latent, nq, k = 1_000_000, 128, 16, 5000, 10
    rng = np.random.default_rng(0)
    Z = rng.normal(size=(n + nq, latent)).astype(np.float32)
    A = rng.normal(size=(latent, dim)).astype(np.float32) / np.sqrt(latent)
    X = (Z @ A).astype(np.float32)
    X += 0.05 * rng.normal(size=X.shape).astype(np.float32)
    db = jnp.asarray(X[:n])
    queries = jnp.asarray(X[n:])
    db.block_until_ready()
    res = DeviceResources(seed=0)

    _, gt = brute_force.knn(res, db, queries, k)
    gt = np.asarray(gt)
    sample = np.arange(0, n, 4001)[:250]
    _, ggt = brute_force.knn(res, db, db[sample], 129)
    ggt = np.asarray(ggt)[:, 1:]

    kg = 129
    p = cagra.IndexParams(graph_degree=64, build_walk_rounds=0)

    def grec(knn):
        g = np.asarray(knn[sample])[:, 1:]  # drop self col for fairness
        return round(sum(len(set(a) & set(b))
                         for a, b in zip(g, ggt)) / ggt.size, 4)

    t0 = time.perf_counter()
    knn = cagra._build_knn_graph_clustered(res, db, kg, p)
    np.asarray(knn[0, 0])
    print(json.dumps({"stage": "scan+rev", "s": round(
        time.perf_counter() - t0, 1), "graph_recall": grec(knn)}),
        flush=True)

    pdim, knn_d = 16, None
    for r in range(1, 4):
        t0 = time.perf_counter()
        knn, knn_d = cagra._graph_refine_round(res, db, knn, kg, p.metric,
                                               pdim, p.build_walk_iters,
                                               knn_d=knn_d)
        np.asarray(knn[0, 0])
        out = {"stage": f"walk_round{r}",
               "s": round(time.perf_counter() - t0, 1),
               "graph_recall": grec(knn)}
        print(json.dumps(out), flush=True)

    # full pipeline check: prune + search recall at the usual points
    ids = jnp.arange(n, dtype=knn.dtype)[:, None]
    order = jnp.argsort(knn == ids, axis=1, stable=True)
    knn_ns = jnp.take_along_axis(knn, order, axis=1)[:, :128].astype(
        jnp.int32)
    t0 = time.perf_counter()
    graph = cagra.prune(res, knn_ns, 64)
    np.asarray(graph[0, 0])
    print(json.dumps({"stage": "prune",
                      "s": round(time.perf_counter() - t0, 1)}),
          flush=True)
    index = cagra.Index(dataset=db, graph=graph, metric=p.metric)
    for itopk in (16, 24, 32, 64):
        sp = cagra.SearchParams(itopk_size=itopk, search_width=1)
        i = index and cagra.search(res, sp, index, queries, k)[1]
        rec = (sum(len(set(a) & set(b)) for a, b in
                   zip(np.asarray(i), gt)) / gt.size)
        t0 = time.perf_counter()
        for _ in range(3):
            i = cagra.search(res, sp, index, queries, k)[1]
        np.asarray(i)
        qps = nq / ((time.perf_counter() - t0) / 3)
        print(json.dumps({"itopk": itopk, "recall": round(rec, 4),
                          "qps": round(qps, 1)}), flush=True)


if __name__ == "__main__":
    main()
