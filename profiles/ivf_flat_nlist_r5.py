"""Diagnose the IVF-Flat nlist=16384 regression (VERDICT r5 item 3):
profile the coarse ranking and the grouped scan separately at the two
conf operating points (4096/np128 vs 16384/np256, equal recall)."""

import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def main():
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/raft_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    import jax.numpy as jnp
    from raft_tpu import DeviceResources
    from raft_tpu.neighbors import ivf_flat, grouped

    n, dim, latent, nq, k = 1_000_000, 128, 16, 5000, 10
    rng = np.random.default_rng(0)
    Z = rng.normal(size=(n + nq, latent)).astype(np.float32)
    A = rng.normal(size=(latent, dim)).astype(np.float32) / np.sqrt(latent)
    X = (Z @ A).astype(np.float32)
    X += 0.05 * rng.normal(size=X.shape).astype(np.float32)
    db = jnp.asarray(X[:n])
    queries = jnp.asarray(X[n:])
    db.block_until_ready()
    res = DeviceResources(seed=0)

    def timeit(fn, reps=5):
        np.asarray(jax.tree_util.tree_leaves(fn())[0])
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn()
        np.asarray(jax.tree_util.tree_leaves(out)[0])
        return (time.perf_counter() - t0) / reps * 1000

    for nlist, nprobe in ((4096, 128), (16384, 256)):
        t0 = time.perf_counter()
        index = ivf_flat.build(
            res, ivf_flat.IndexParams(n_lists=nlist), db)
        np.asarray(index.list_sizes[0])
        build_s = time.perf_counter() - t0
        cap = index.capacity

        coarse_ms = timeit(lambda: ivf_flat._select_clusters(
            index.centers, queries, nprobe, index.metric))
        probes = ivf_flat._select_clusters(index.centers, queries,
                                           nprobe, index.metric)
        ng = int(grouped.num_groups(probes, nlist))
        search_ms = timeit(lambda: ivf_flat.search(
            res, ivf_flat.SearchParams(n_probes=nprobe), index,
            queries, k))
        print(json.dumps({
            "nlist": nlist, "nprobe": nprobe, "cap": cap,
            "build_s": round(build_s, 1), "n_groups": ng,
            "pairs": nq * nprobe,
            "coarse_ms": round(coarse_ms, 1),
            "search_ms": round(search_ms, 1),
            "qps": round(nq / (search_ms / 1000), 1)}), flush=True)


if __name__ == "__main__":
    main()
