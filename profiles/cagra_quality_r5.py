"""Bisect the r5 build-quality regression at 1M: which knob recovers
r4's walk recall (0.96 @ itopk 24)?  Variants share the dataset/GT."""

import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def main():
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/raft_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    import jax.numpy as jnp
    from raft_tpu import DeviceResources
    from raft_tpu.neighbors import brute_force, cagra

    n, dim, latent, nq, k = 1_000_000, 128, 16, 5000, 10
    rng = np.random.default_rng(0)
    Z = rng.normal(size=(n + nq, latent)).astype(np.float32)
    A = rng.normal(size=(latent, dim)).astype(np.float32) / np.sqrt(latent)
    X = (Z @ A).astype(np.float32)
    X += 0.05 * rng.normal(size=X.shape).astype(np.float32)
    db = jnp.asarray(X[:n])
    queries = jnp.asarray(X[n:])
    db.block_until_ready()
    res = DeviceResources(seed=0)

    _, gt = brute_force.knn(res, db, queries, k)
    gt = np.asarray(gt)
    sample = np.arange(0, n, 4001)[:250]
    _, ggt = brute_force.knn(res, db, db[sample], 129)
    ggt = np.asarray(ggt)[:, 1:]

    variants = {
        "A_default": {},
        "C_rev2": {"build_reverse_rounds": 2},
        "D_t64": {"build_n_probes": 64},
        "B_maxed": {"build_proj_dim": 128, "build_n_probes": 64,
                    "build_scan_recall": 0.98,
                    "build_reverse_rounds": 2},
    }
    for name, kw in variants.items():
        p = cagra.IndexParams(graph_degree=64, **kw)
        t0 = time.perf_counter()
        knn = cagra.build_knn_graph(res, db, p.intermediate_graph_degree,
                                    params=p)
        np.asarray(knn[0, 0])
        t_graph = time.perf_counter() - t0
        g = np.asarray(knn[sample])
        grec = (sum(len(set(a) & set(b)) for a, b in zip(g, ggt))
                / ggt.size)
        t0 = time.perf_counter()
        graph = cagra.prune(res, knn, p.graph_degree)
        np.asarray(graph[0, 0])
        t_prune = time.perf_counter() - t0
        index = cagra.Index(dataset=db, graph=graph, metric=p.metric)
        out = {"variant": name, "knn_s": round(t_graph, 1),
               "prune_s": round(t_prune, 1),
               "graph_recall128": round(grec, 4)}
        for itopk in (24, 64):
            sp = cagra.SearchParams(itopk_size=itopk, search_width=1)
            i = cagra.search(res, sp, index, queries, k)[1]
            rec = (sum(len(set(a) & set(b)) for a, b in
                       zip(np.asarray(i), gt)) / gt.size)
            out[f"walk_recall@{itopk}"] = round(rec, 4)
        print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
