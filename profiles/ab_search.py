"""A/B: probe-order vs grouped IVF-PQ recon search at the bench workload.

Run on the real chip:  python profiles/ab_search.py [--trace]
Times each impl with host-readback timing; --trace captures a profiler
trace of both variants under profiles/.
"""

import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, ".")
    import bench
    from raft_tpu import DeviceResources
    from raft_tpu.neighbors import ivf_pq

    bench._setup_jax_cache()
    res = DeviceResources(seed=0)
    db, queries = bench._make_dataset({"n_db": 1_000_000, "dim": 128,
                                       "latent_dim": 16, "noise": 0.05,
                                       "n_queries": 5_000})
    params = ivf_pq.IndexParams(n_lists=4096, pq_dim=64, kmeans_n_iters=20)
    t0 = time.perf_counter()
    index = ivf_pq.build(res, params, db)
    jax.block_until_ready(index.list_codes)
    print("build_s", round(time.perf_counter() - t0, 1))

    n_probes = 96
    k = 20
    m = index.metric

    from raft_tpu.neighbors import grouped

    probes = ivf_pq._select_clusters(index.centers, index.rotation,
                                     queries, n_probes, m)
    n_groups = grouped.round_groups(
        int(grouped.num_groups(probes, index.n_lists)))
    cap = index.capacity
    G, rot = grouped.GROUP, index.rot_dim
    block = grouped.block_size(n_groups, G * cap * 8, cap * rot * 2,
                               G * rot * 4)
    print("n_groups", n_groups, "cap", cap, "block", block)

    def run_probe_order():
        d, i = ivf_pq._search_impl_recon(
            index.centers, index.list_recon, index.list_indices,
            index.rotation, queries, k, n_probes, m,
            list_recon_sq=index.list_recon_sq)
        return i

    def run_grouped(p):
        d, i = ivf_pq._search_impl_recon_grouped(
            index.centers, index.list_recon, index.list_recon_sq,
            index.list_indices, index.rotation, queries, p, k, m,
            n_groups, block)
        return i

    def run_grouped_with_sync():
        p = ivf_pq._select_clusters(index.centers, index.rotation,
                                    queries, n_probes, m)
        _ = grouped.round_groups(int(grouped.num_groups(p, index.n_lists)))
        return run_grouped(p)

    def run_grouped_pallas(p):
        d, i = ivf_pq._search_impl_recon_grouped(
            index.centers, index.list_recon, index.list_recon_sq,
            index.list_indices, index.rotation, queries, p, k, m,
            n_groups, block, use_pallas=True)
        return i

    variants = [("probe_order", run_probe_order),
                ("grouped_presel", lambda: run_grouped(probes)),
                ("grouped_pallas", lambda: run_grouped_pallas(probes)),
                ("grouped_sync", run_grouped_with_sync)]
    for name, fn in variants:
        i = fn()
        np.asarray(i)                    # warm
        t0 = time.perf_counter()
        for _ in range(3):
            i = fn()
        np.asarray(i)
        dt = (time.perf_counter() - t0) / 3
        print(f"{name}: {dt*1000:.1f} ms/batch  ({5000/dt:.0f} qps)")

    if "--trace" in sys.argv:
        with jax.profiler.trace("profiles/ab_trace"):
            np.asarray(run_probe_order())
            np.asarray(run_grouped(probes))
        print("trace written to profiles/ab_trace")


if __name__ == "__main__":
    main()
