"""Micro-bench: random row gather from a (1M, 128) table on TPU.

CAGRA's greedy walk gathers (q * search_width * degree) scattered dataset
rows per iteration; this measures the candidate implementations so the
search-loop design is driven by data (round 4):

  a) XLA jnp.take (the round-3 search path), f32 and bf16
  b) Pallas kernel: per-block SMEM ids drive per-row double-buffered
     HBM->VMEM DMAs (embedding-lookup pattern)

Timing reduces the gathered block to one scalar and reads it back
(block_until_ready has been observed returning early over the remote
tunnel — see PERFORMANCE.md).
"""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N, D = 1_000_000, 128
M = 5_000 * 64          # rows gathered per search iteration (q * w * degree)


def timeit(fn, *args, iters=20):
    np.asarray(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    np.asarray(out)
    return (time.perf_counter() - t0) / iters


@jax.jit
def xla_take(table, ids):
    return jnp.sum(jnp.take(table, ids, axis=0).astype(jnp.float32))


# ------------------------------------------------------- Pallas DMA gather
def _gather_kernel(ids_ref, table_ref, out_ref, scratch, sems, *, rows):
    def issue(i, slot):
        row = ids_ref[i]
        return pltpu.make_async_copy(
            table_ref.at[pl.ds(row, 1)], scratch.at[pl.ds(slot, 1)],
            sems.at[slot])

    # double-buffered row DMAs: issue row i+1 while waiting on row i
    issue(0, 0).start()

    def body(i, _):
        slot = jax.lax.rem(i, 2)
        nxt = 1 - slot

        @pl.when(i + 1 < rows)
        def _():
            issue(i + 1, nxt).start()

        issue(i, slot).wait()
        out_ref[pl.ds(i, 1)] = scratch[pl.ds(slot, 1)]
        return 0

    jax.lax.fori_loop(0, rows, body, 0)


@functools.partial(jax.jit, static_argnames=("rows",))
def pallas_gather(table, ids, rows=512):
    m = ids.shape[0]
    grid = m // rows
    return pl.pallas_call(
        functools.partial(_gather_kernel, rows=rows),
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((rows,), lambda b: (b,), memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec((rows, table.shape[1]), lambda b: (b, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, table.shape[1]), table.dtype),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        out_shape=jax.ShapeDtypeStruct((m, table.shape[1]), table.dtype),
    )(ids, table)


@functools.partial(jax.jit, static_argnames=("rows",))
def pallas_gather_sum(table, ids, rows=512):
    return jnp.sum(pallas_gather(table, ids, rows).astype(jnp.float32))


def main():
    rng = np.random.default_rng(0)
    table = jnp.asarray(rng.normal(size=(N, D)).astype(np.float32))
    table_bf16 = table.astype(jnp.bfloat16)
    ids = jnp.asarray(rng.integers(0, N, size=M).astype(np.int32))

    bytes_f32 = M * D * 4
    bytes_bf16 = M * D * 2

    t = timeit(xla_take, table, ids)
    print(f"xla_take f32 : {t*1e3:7.2f} ms  {bytes_f32/t/1e9:7.1f} GB/s")
    t = timeit(xla_take, table_bf16, ids)
    print(f"xla_take bf16: {t*1e3:7.2f} ms  {bytes_bf16/t/1e9:7.1f} GB/s")
    for rows in (1024, 2048):
        try:
            t = timeit(pallas_gather_sum, table, ids, rows)
            print(f"pallas f32 rows={rows:5d}: {t*1e3:7.2f} ms  "
                  f"{bytes_f32/t/1e9:7.1f} GB/s")
            t = timeit(pallas_gather_sum, table_bf16, ids, rows)
            print(f"pallas bf16 rows={rows:5d}: {t*1e3:7.2f} ms  "
                  f"{bytes_bf16/t/1e9:7.1f} GB/s")
        except Exception as e:  # noqa: BLE001 - report and continue
            print(f"pallas rows={rows} failed: {type(e).__name__}: "
                  f"{str(e)[:200]}")
    # correctness spot check
    a = float(xla_take(table, ids[:4096]))
    b = float(pallas_gather_sum(table, ids[:4096], 1024))
    print("match:", np.isclose(a, b, rtol=1e-6))


if __name__ == "__main__":
    main()
