"""Profile the k-means Lloyd loop at the bench workload (1M x 128, k=1024).

Run on the real chip:  python profiles/profile_kmeans.py
Prints fit timing (wall clock + the observability ``kmeans.fit`` timer
and iteration counter) and writes a trace under profiles/kmeans_trace.
"""

import json
import sys
import time

import numpy as np


def main():
    import jax

    sys.path.insert(0, ".")
    import bench
    from raft_tpu import DeviceResources
    from raft_tpu import observability as obs
    from raft_tpu.cluster import kmeans
    from raft_tpu.cluster.kmeans_types import InitMethod, KMeansParams

    bench._setup_jax_cache()
    res = DeviceResources(seed=0)
    db, _ = bench._make_dataset({"n_db": 1_000_000, "dim": 128,
                                 "latent_dim": 16, "noise": 0.05,
                                 "n_queries": 1})
    params = KMeansParams(n_clusters=1024, max_iter=20, tol=0.0, n_init=1,
                          init=InitMethod.Random)
    c, _, _ = kmeans.fit(res, params, db)     # warm
    np.asarray(c)
    obs.reset()
    t0 = time.perf_counter()
    with obs.collecting():
        c, inertia, n_iter = kmeans.fit(res, params, db)
        np.asarray(c)
    dt = time.perf_counter() - t0
    print(f"fit: {dt*1000:.0f} ms  ({20/dt:.1f} iter/s)")
    print(json.dumps(obs.snapshot(), default=str), flush=True)

    with jax.profiler.trace("profiles/kmeans_trace"):
        c, inertia, n_iter = kmeans.fit(res, params, db)
        np.asarray(c)
    print("trace written to profiles/kmeans_trace")


if __name__ == "__main__":
    main()
