"""Round-5 k-means kernel experiments: find where the fused pass's
time goes and which variant clears 80 it/s at 1M x 128, k=1024.

Variants:
  base      — current fused_assign_update (tile sweep)
  nodmin    — drop the dmin output (plain Lloyd does not need it)
  uw        — uniform-weight specialization (onehot straight to bf16,
              no w multiply; counts from the f32 one-hot sum)
  mxuonly   — distance matmul only (no epilogue): isolates the MXU
              floor so the epilogue's share is measurable
"""

import functools
import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir", "/tmp/raft_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import jax.numpy as jnp  # noqa: E402
from jax.experimental import pallas as pl  # noqa: E402

from raft_tpu.ops.kmeans_update_pallas import (  # noqa: E402
    _round_up,
    fused_assign_update,
)


def _kernel_uw(x_ref, c_ref, csq_ref, sums_ref, counts_ref, dmin_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        sums_ref[...] = jnp.zeros_like(sums_ref)
        counts_ref[...] = jnp.zeros_like(counts_ref)

    x = x_ref[...]
    ip = jax.lax.dot_general(x, c_ref[...], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    d = csq_ref[...] - 2.0 * ip
    labels = jnp.argmin(d, axis=1)
    dmin_ref[...] = jnp.min(d, axis=1, keepdims=True)
    cols = jax.lax.broadcasted_iota(jnp.int32, d.shape, 1)
    onehot = (cols == labels[:, None]).astype(jnp.bfloat16)
    sums_ref[...] += jax.lax.dot_general(
        onehot, x, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    counts_ref[...] += jnp.sum(onehot.astype(jnp.float32), axis=0,
                               keepdims=True)


def _kernel_mxuonly(x_ref, c_ref, csq_ref, sums_ref, counts_ref,
                    dmin_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        sums_ref[...] = jnp.zeros_like(sums_ref)
        counts_ref[...] = jnp.zeros_like(counts_ref)

    x = x_ref[...]
    ip = jax.lax.dot_general(x, c_ref[...], (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    d = csq_ref[...] - 2.0 * ip
    dmin_ref[...] = jnp.min(d, axis=1, keepdims=True)
    counts_ref[...] += jnp.sum(d, axis=0, keepdims=True)  # placeholder


@functools.partial(jax.jit, static_argnames=("tile", "which"))
def run_variant(x, centroids, tile, which):
    n, dim = x.shape
    k = centroids.shape[0]
    n_pad = _round_up(n, tile)
    k_pad = _round_up(k, 128)
    d_pad = _round_up(dim, 128)
    cf = centroids.astype(jnp.float32)
    c_sq = jnp.sum(cf * cf, axis=1)
    csq_p = jnp.full((1, k_pad), jnp.inf, jnp.float32).at[0, :k].set(c_sq)
    c_p = jnp.zeros((k_pad, d_pad), jnp.bfloat16).at[:k, :dim].set(
        cf.astype(jnp.bfloat16))
    x_p = jnp.zeros((n_pad, d_pad), jnp.bfloat16).at[:n, :dim].set(
        x.astype(jnp.bfloat16))
    kern = {"uw": _kernel_uw, "mxuonly": _kernel_mxuonly}[which]
    sums, counts, dmin = pl.pallas_call(
        kern,
        grid=(n_pad // tile,),
        in_specs=[
            pl.BlockSpec((tile, d_pad), lambda i: (i, 0)),
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, k_pad), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, k_pad), lambda i: (0, 0)),
            pl.BlockSpec((tile, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((k_pad, d_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, k_pad), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        ],
    )(x_p, c_p, csq_p)
    return sums[:k, :dim], counts[0, :k], dmin[:n, 0]


def time_it(fn, reps=10):
    out = fn()
    np.asarray(jax.tree_util.tree_leaves(out)[0])    # forced warm readback
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn()
    np.asarray(jax.tree_util.tree_leaves(out)[0])
    return (time.perf_counter() - t0) / reps


def main():
    n, dim, k = 1_000_000, 128, 1024
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n, dim)).astype(np.float32))
    c = jnp.asarray(rng.normal(size=(k, dim)).astype(np.float32))
    ones = jnp.ones((n,), jnp.float32)
    x.block_until_ready()

    for tile in (512, 1024, 2048):
        try:
            ms = time_it(lambda: fused_assign_update(x, ones, c,
                                                     tile=tile)) * 1e3
            print(json.dumps({"variant": "base", "tile": tile,
                              "ms": round(ms, 2)}), flush=True)
        except Exception as e:                        # VMEM overflow etc
            print(json.dumps({"variant": "base", "tile": tile,
                              "error": str(e)[:120]}), flush=True)
    for which in ("uw", "mxuonly"):
        for tile in (1024, 2048):
            try:
                ms = time_it(lambda: run_variant(x, c, tile, which)) * 1e3
                print(json.dumps({"variant": which, "tile": tile,
                                  "ms": round(ms, 2)}), flush=True)
            except Exception as e:
                print(json.dumps({"variant": which, "tile": tile,
                                  "error": str(e)[:120]}), flush=True)
    # correctness spot-check: uw matches base on a slice
    s0, c0, d0 = fused_assign_update(x[:65536], ones[:65536], c, tile=1024)
    s1, c1, d1 = run_variant(x[:65536], c, 1024, "uw")
    print(json.dumps({
        "uw_sums_close": bool(jnp.allclose(s0, s1, rtol=1e-3, atol=1e-2)),
        "uw_counts_equal": bool(jnp.array_equal(c0, c1)),
        "uw_dmin_close": bool(jnp.allclose(d0, d1, rtol=1e-3,
                                           atol=1e-2))}), flush=True)


if __name__ == "__main__":
    main()
