"""Decompose the grouped-scan kernel's ~22.5 us/group flat cost
(measured round 5: same per-group time at cap 160 and cap 416):
variants remove the one-hot query gather and/or the in-VMEM top-kt
extraction to see where the time actually goes."""

import functools
import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import jax  # noqa: E402

jax.config.update("jax_compilation_cache_dir", "/tmp/raft_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

import jax.numpy as jnp  # noqa: E402
from jax.experimental import pallas as pl  # noqa: E402
from jax.experimental.pallas import tpu as pltpu  # noqa: E402

from raft_tpu.neighbors.grouped import GROUP  # noqa: E402
from raft_tpu.ops import pq_group_scan_pallas as pqp  # noqa: E402


def _kernel_var(gl_ref, slot_ref, q_ref, data_ref, dsq_ref, ids_ref,
                *outs, kt, n_probes, P, gather, extract):
    if gather:
        qv = pqp._gather_queries(slot_ref, q_ref, n_probes, P)
    else:
        qv = q_ref[0]                                   # pre-gathered (G, d)
    q_sq = jnp.sum(qv * qv, axis=1)
    data = data_ref[0]
    ip = jax.lax.dot_general(qv, data, (((1,), (1,)), ((), ())),
                             precision=jax.lax.Precision.HIGHEST,
                             preferred_element_type=jnp.float32)
    d = jnp.maximum(q_sq[:, None] + dsq_ref[0, 0][None, :] - 2.0 * ip, 0.0)
    if extract:
        vals_ref, ids_out_ref, vs, ps = outs
        pqp._extract_topk(d, ids_ref[0, 0], vals_ref, ids_out_ref, vs, ps,
                          kt)
    else:
        outs[0][0] = d                                  # raw block out


@functools.partial(jax.jit, static_argnames=("kt", "n_probes", "gather",
                                             "extract"))
def run_var(group_list, slot_pairs, q_in, list_data, d_sq, list_indices,
            kt, n_probes, gather, extract):
    n_groups = group_list.shape[0]
    _, cap, dim = list_data.shape
    nq = q_in.shape[0] if gather else 0
    P = slot_pairs.shape[0] * GROUP  # upper bound, fine for sentinel math

    if gather:
        nq_pad = -(-(nq + 1) // 128) * 128
        q_pad = jnp.zeros((nq_pad, dim), jnp.float32).at[:nq].set(q_in)
        q_spec = pl.BlockSpec((nq_pad, dim), lambda g, gl: (0, 0))
    else:
        q_pad = q_in                                    # (n_groups, G, dim)
        q_spec = pl.BlockSpec((1, GROUP, dim), lambda g, gl: (g, 0, 0))

    outs_spec = ([pl.BlockSpec((1, GROUP, kt), lambda g, gl: (g, 0, 0)),
                  pl.BlockSpec((1, GROUP, kt), lambda g, gl: (g, 0, 0))]
                 if extract else
                 [pl.BlockSpec((1, GROUP, cap), lambda g, gl: (g, 0, 0))])
    outs_shape = ([jax.ShapeDtypeStruct((n_groups, GROUP, kt), jnp.float32),
                   jax.ShapeDtypeStruct((n_groups, GROUP, kt), jnp.int32)]
                  if extract else
                  [jax.ShapeDtypeStruct((n_groups, GROUP, cap),
                                        jnp.float32)])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_groups,),
        in_specs=[
            pl.BlockSpec((1, 1, GROUP), lambda g, gl: (g, 0, 0)),
            q_spec,
            pl.BlockSpec((1, cap, dim), lambda g, gl: (gl[g], 0, 0)),
            pl.BlockSpec((1, 1, cap), lambda g, gl: (gl[g], 0, 0)),
            pl.BlockSpec((1, 1, cap), lambda g, gl: (gl[g], 0, 0)),
        ],
        out_specs=outs_spec,
        scratch_shapes=pqp._scratch_shapes(kt) if extract else [],
    )
    return pl.pallas_call(
        functools.partial(_kernel_var, kt=kt, n_probes=n_probes, P=P,
                          gather=gather, extract=extract),
        out_shape=outs_shape, grid_spec=grid_spec,
    )(group_list, slot_pairs[:, None, :], q_pad, list_data, d_sq[:, None, :],
      list_indices[:, None, :])


def main():
    from raft_tpu import DeviceResources
    from raft_tpu.neighbors import grouped, ivf_flat

    n, dim, latent, nq = 1_000_000, 128, 16, 5000
    rng = np.random.default_rng(0)
    Z = rng.normal(size=(n + nq, latent)).astype(np.float32)
    A = rng.normal(size=(latent, dim)).astype(np.float32) / np.sqrt(latent)
    X = (Z @ A + 0.05 * rng.normal(
        size=(n + nq, dim))).astype(np.float32)
    db = jnp.asarray(X[:n])
    queries = jnp.asarray(X[n:])
    db.block_until_ready()
    res = DeviceResources(seed=0)

    def timeit(fn, reps=5):
        np.asarray(jax.tree_util.tree_leaves(fn())[0]).ravel()[:1]
        t0 = time.perf_counter()
        for _ in range(reps):
            o = fn()
        np.asarray(jax.tree_util.tree_leaves(o)[0]).ravel()[:1]
        return (time.perf_counter() - t0) / reps * 1e3

    for nlist, nprobe in ((16384, 256), (4096, 128)):
        index = ivf_flat.build(res, ivf_flat.IndexParams(n_lists=nlist), db)
        probes = ivf_flat._select_clusters(index.centers, queries, nprobe,
                                           index.metric)
        ng = grouped.round_groups(int(grouped.num_groups(probes, nlist)))
        gl, sp = grouped.build_groups(probes, nlist, ng)
        dsq = jnp.sum(index.list_data.astype(jnp.float32) ** 2, axis=-1)
        ld = index.list_data.astype(jnp.float32)
        qf = queries.astype(jnp.float32)
        # pre-gathered queries for the no-onehot variants
        P = nq * nprobe
        qid = jnp.where(sp < P, sp // nprobe, 0)        # (ng, G)
        qg = qf[qid]                                    # (ng, G, dim)
        kt = 10
        for gather in (True, False):
            for extract in (True, False):
                q_in = qf if gather else qg
                try:
                    ms = timeit(lambda: run_var(
                        gl, sp, q_in, ld, dsq, index.list_indices, kt,
                        nprobe, gather, extract))
                    print(json.dumps({
                        "nlist": nlist, "n_groups": ng,
                        "gather": gather, "extract": extract,
                        "ms": round(ms, 1),
                        "us_per_group": round(ms * 1e3 / ng, 2)}),
                        flush=True)
                except Exception as e:
                    print(json.dumps({"nlist": nlist, "gather": gather,
                                      "extract": extract,
                                      "error": str(e)[:120]}), flush=True)
        # cost of producing the pre-gathered queries (XLA gather)
        ms = timeit(lambda: qf[jnp.where(sp < P, sp // nprobe, 0)])
        print(json.dumps({"nlist": nlist, "xla_query_gather_ms":
                          round(ms, 1)}), flush=True)


if __name__ == "__main__":
    main()
