"""Ceiling of the candidate pool vs clustering granularity at 1M:
for several n_lists, what fraction of the exact top-129 lives in
(a) the query's LIST's top-t lists (per-list probing — the r5 scan),
(b) the QUERY's own top-t lists (per-query probing — the reference),
with t sized for a ~8k/16k-row candidate pool."""

import json
import sys

import numpy as np

sys.path.insert(0, "/root/repo")


def main():
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/raft_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    import jax.numpy as jnp
    from raft_tpu import DeviceResources
    from raft_tpu.cluster import kmeans_balanced
    from raft_tpu.distance.types import DistanceType
    from raft_tpu.neighbors import brute_force

    n, dim, latent = 1_000_000, 128, 16
    rng = np.random.default_rng(0)
    Z = rng.normal(size=(n, latent)).astype(np.float32)
    A = rng.normal(size=(latent, dim)).astype(np.float32) / np.sqrt(latent)
    X = (Z @ A).astype(np.float32)
    X += 0.05 * rng.normal(size=X.shape).astype(np.float32)
    db = jnp.asarray(X)
    db.block_until_ready()
    res = DeviceResources(seed=0)

    sample = np.arange(0, n, 4001)[:250]
    _, ggt = brute_force.knn(res, db, db[sample], 129)
    ggt = np.asarray(ggt)

    bal = kmeans_balanced.KMeansBalancedParams(
        n_iters=10, metric=DistanceType.L2Expanded)
    for n_lists in (500, 1000, 2000, 4000):
        n_train = min(n, max(n_lists * 8, max(65536, n // 10)))
        trainset = db[::max(n // n_train, 1)][:n_train]
        centers = kmeans_balanced.fit(res, bal, trainset, n_lists)
        labels = np.asarray(kmeans_balanced.predict(res, bal, db, centers))
        cnp = np.asarray(centers)
        c_sq = (cnp * cnp).sum(1)
        # per-list ranking (center-center) and per-query ranking
        for pool_target in (8192, 16384):
            t = max(4, int(round(pool_target / (n / n_lists))))
            t = min(t, n_lists)
            dcc = c_sq[None, :] - 2.0 * (cnp @ cnp.T)
            np.fill_diagonal(dcc, -np.inf)
            nbrs = np.argsort(dcc, axis=1)[:, :t]
            member = [set(r.tolist()) for r in nbrs]
            q = X[sample]
            dqc = c_sq[None, :] - 2.0 * (q @ cnp.T)
            qnbrs = np.argsort(dqc, axis=1)[:, :t]
            okl = okq = tot = 0
            for row, qi, g in zip(range(len(sample)), sample, ggt):
                cl = member[labels[qi]]
                cq = set(qnbrs[row].tolist())
                for j in g:
                    lj = labels[j]
                    okl += lj in cl
                    okq += lj in cq
                tot += len(g)
            print(json.dumps({
                "n_lists": n_lists, "t": t, "pool": pool_target,
                "per_list": round(okl / tot, 4),
                "per_query": round(okq / tot, 4)}), flush=True)


if __name__ == "__main__":
    main()
