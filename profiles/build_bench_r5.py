"""Round-5 CAGRA build timing breakdown at 1M x 128 (graph / prune /
pack), plus search QPS spot-check — the VERDICT r5 item-1 gate
(build_s <= 60 with unchanged search QPS/recall)."""

import json
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")


def main():
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/raft_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    import jax.numpy as jnp
    from raft_tpu import DeviceResources
    from raft_tpu.neighbors import brute_force, cagra

    n, dim, latent, nq, k = 1_000_000, 128, 16, 5000, 10
    rng = np.random.default_rng(0)
    Z = rng.normal(size=(n + nq, latent)).astype(np.float32)
    A = rng.normal(size=(latent, dim)).astype(np.float32) / np.sqrt(latent)
    X = (Z @ A).astype(np.float32)
    X += 0.05 * rng.normal(size=X.shape).astype(np.float32)
    db = jnp.asarray(X[:n])
    queries = jnp.asarray(X[n:])
    db.block_until_ready()
    res = DeviceResources(seed=0)

    p = cagra.IndexParams(graph_degree=64)

    t0 = time.perf_counter()
    knn = cagra.build_knn_graph(res, db, p.intermediate_graph_degree,
                                params=p)
    np.asarray(knn[0, 0])
    t_graph = time.perf_counter() - t0
    print(json.dumps({"stage": "knn_graph", "s": round(t_graph, 1)}),
          flush=True)

    t0 = time.perf_counter()
    graph = cagra.prune(res, knn, p.graph_degree)
    np.asarray(graph[0, 0])
    t_prune = time.perf_counter() - t0
    print(json.dumps({"stage": "prune", "s": round(t_prune, 1)}),
          flush=True)
    index = cagra.Index(dataset=db, graph=graph, metric=p.metric)

    # graph quality: recall of knn graph vs exact on a sample
    _, gt = brute_force.knn(res, db, queries, k)
    gt = np.asarray(gt)

    # walk-table build (the "pack" stage) happens on first search
    sp = cagra.SearchParams(itopk_size=24, search_width=1)
    t0 = time.perf_counter()
    i = cagra.search(res, sp, index, queries, k)[1]
    np.asarray(i)
    t_pack = time.perf_counter() - t0
    print(json.dumps({"stage": "pack+first_search",
                      "s": round(t_pack, 1)}), flush=True)

    for itopk in (16, 24, 32, 64):
        sp = cagra.SearchParams(itopk_size=itopk, search_width=1)
        i = cagra.search(res, sp, index, queries, k)[1]
        rec = (sum(len(set(a) & set(b)) for a, b in
                   zip(np.asarray(i), gt)) / gt.size)
        t0 = time.perf_counter()
        for _ in range(3):
            i = cagra.search(res, sp, index, queries, k)[1]
        np.asarray(i)
        qps = nq / ((time.perf_counter() - t0) / 3)
        print(json.dumps({"itopk": itopk, "recall": round(rec, 4),
                          "qps": round(qps, 1)}), flush=True)

    print(json.dumps({"build_total_s": round(t_graph + t_prune, 1)}),
          flush=True)


if __name__ == "__main__":
    main()
