"""A/B harness for the CAGRA search paths (round 4).

Builds a SIFT-like index at --n scale on the live chip, then sweeps
operating points over the packed-neighborhood walk (walk_pdim>0) and the
direct exact walk (walk_pdim=0), reporting QPS + recall@10 vs
brute-force ground truth.

Build artifacts are cached under /tmp (--cache): the remote tunnel can
wedge a long-running process, and a cached GT + serialized index make
the sweep restartable without paying the build again.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, ".")   # run from the repo root: python profiles/ab_cagra.py


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=100_000)
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--nq", type=int, default=5_000)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--degree", type=int, default=64)
    ap.add_argument("--cache", default="/tmp/ab_cagra_cache")
    ap.add_argument("--skip-direct", action="store_true")
    args = ap.parse_args()

    import jax
    jax.config.update("jax_compilation_cache_dir", "/tmp/raft_tpu_jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

    from raft_tpu import DeviceResources
    from raft_tpu.neighbors import brute_force, cagra

    rng = np.random.default_rng(0)
    latent = 16
    Z = rng.normal(size=(args.n + args.nq, latent)).astype(np.float32)
    A = rng.normal(size=(latent, args.dim)).astype(np.float32) / np.sqrt(latent)
    X = (Z @ A).astype(np.float32)
    X += 0.05 * rng.normal(size=X.shape).astype(np.float32)
    import jax.numpy as jnp
    X = jnp.asarray(X)
    db, q = X[:args.n], X[args.n:]

    res = DeviceResources(seed=0)
    print("data ready", flush=True)
    os.makedirs(args.cache, exist_ok=True)
    tag = f"{args.n}_{args.dim}_{args.degree}"
    gt_path = os.path.join(args.cache, f"gt_{tag}.npy")
    idx_path = os.path.join(args.cache, f"idx_{tag}.bin")

    if os.path.exists(gt_path):
        gt = np.load(gt_path)
        print("gt loaded", flush=True)
    else:
        t0 = time.perf_counter()
        _, gt = brute_force.knn(res, db, q, args.k)
        gt = np.asarray(gt)
        np.save(gt_path, gt)
        print(json.dumps({"gt_s": round(time.perf_counter() - t0, 1)}),
              flush=True)

    if os.path.exists(idx_path):
        with open(idx_path, "rb") as f:
            index = cagra.deserialize(res, f)
        # the serialized graph is the artifact; search against the
        # in-memory dataset (identical content)
        index.dataset = db
        print("index loaded", flush=True)
    else:
        t0 = time.perf_counter()
        index = cagra.build(res, cagra.IndexParams(graph_degree=args.degree),
                            db)
        np.asarray(index.graph[0, 0])
        print(json.dumps({"build_s": round(time.perf_counter() - t0, 1),
                          "n": args.n}), flush=True)
        with open(idx_path, "wb") as f:
            cagra.serialize(res, f, index)
        print("index saved", flush=True)

    def run(sp, runs=3):
        d, i = cagra.search(res, sp, index, q, args.k)
        rec = sum(len(set(a) & set(b))
                  for a, b in zip(np.asarray(i), gt)) / gt.size
        t0 = time.perf_counter()
        for _ in range(runs):
            d, i = cagra.search(res, sp, index, q, args.k)
        np.asarray(i)
        qps = args.nq / ((time.perf_counter() - t0) / runs)
        return rec, qps

    points = [
        dict(itopk_size=16, search_width=1),
        dict(itopk_size=16, search_width=2),
        dict(itopk_size=24, search_width=1),
        dict(itopk_size=32, search_width=1),
        dict(itopk_size=32, search_width=2),
        dict(itopk_size=64, search_width=1),
        dict(itopk_size=64, search_width=2),
        dict(itopk_size=64, search_width=4),
        dict(itopk_size=96, search_width=2),
    ]
    for walk in (None, 0):
        if walk == 0 and args.skip_direct:
            break
        for pt in points:
            sp = cagra.SearchParams(walk_pdim=walk, **pt)
            rec, qps = run(sp)
            print(json.dumps({"walk_pdim": walk, **pt,
                              "recall": round(rec, 4),
                              "qps": round(qps, 1)}), flush=True)


if __name__ == "__main__":
    main()
