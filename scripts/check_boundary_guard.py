#!/usr/bin/env python
"""CI guard shim: the boundary-validator lint now lives in graftlint.

The real pass is ``scripts/graftlint/passes/boundary_guard.py`` (run it
with ``python -m scripts.graftlint --rules boundary-guard``); this
wrapper keeps the historical script entry point and its ``check_file``
/ ``main`` API for callers that load it by path.

Usage: python scripts/check_boundary_guard.py   (exits 1 on violations)
"""

from __future__ import annotations

import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
if str(ROOT) not in sys.path:
    sys.path.insert(0, str(ROOT))

from scripts.graftlint import core as _core  # noqa: E402
from scripts.graftlint.passes import boundary_guard as _pass  # noqa: E402

PACKAGES = {p.rstrip("/"): m for p, m in _pass.PACKAGES.items()}
GUARDED = _pass.GUARDED
VALIDATORS = _pass.VALIDATORS


def check_file(path: pathlib.Path, mode: str = "functions") -> list:
    path = pathlib.Path(path)
    try:
        rel = str(path.relative_to(ROOT))
    except ValueError:
        rel = str(path)
    mod = _core.Module(rel, path.read_text())
    return [str(d) for d in _pass.check_module(mod, mode)
            if not mod.suppressed(d.line, d.rule)]


def main() -> int:
    violations = []
    for pkg, mode in PACKAGES.items():
        for path in sorted((ROOT / pkg).glob("*.py")):
            violations.extend(check_file(path, mode))
    for v in violations:
        print(v)
    if violations:
        print(f"\n{len(violations)} unguarded entry point(s); wire "
              "check_matrix/guard_nonfinite at the boundary (see "
              "docs/api.md, 'Integrity & validation').")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
