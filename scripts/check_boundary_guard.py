#!/usr/bin/env python
"""CI guard: public entry points must run the boundary validator.

Every module-level public entry point in ``raft_tpu/neighbors`` and
``raft_tpu/cluster`` that accepts user arrays (build / search / extend /
fit / predict / ...) must route them through
``raft_tpu.integrity.boundary`` (``check_matrix`` / ``guard_nonfinite``),
either directly or by delegating to a same-module function that does.
This keeps the PR 4 input-hardening contract from silently eroding as
entry points are added.

Usage: python scripts/check_boundary_guard.py   (exits 1 on violations)
"""

from __future__ import annotations

import ast
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
# package -> scan mode: "functions" checks module-level entry points
# only; "all" also checks methods of module-level classes (the serving
# surface is class-shaped: Server.submit / Server.search)
PACKAGES = {
    "raft_tpu/neighbors": "functions",
    "raft_tpu/cluster": "functions",
    "raft_tpu/serving": "all",
}

# entry-point names that take user arrays and must validate them
GUARDED = {
    "build", "search", "extend", "fit", "predict", "transform",
    "fit_predict", "knn", "knn_query", "all_knn_query", "build_index",
    "eps_neighbors_l2sq", "refine", "submit", "upsert",
}
VALIDATORS = {"check_matrix", "guard_nonfinite"}


def _calls_validator(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) and node.attr in VALIDATORS:
            return True
        if isinstance(node, ast.Name) and node.id in VALIDATORS:
            return True
    return False


def _local_callees(fn: ast.FunctionDef) -> set:
    """Names a function may delegate to: direct calls, but also bare
    references (``raw(fit)(...)`` wraps ``fit`` before calling it)."""
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.Attribute):
            out.add(node.attr)
    return out


def check_file(path: pathlib.Path, mode: str = "functions") -> list:
    tree = ast.parse(path.read_text(), filename=str(path))
    fns = {n.name: n for n in tree.body if isinstance(n, ast.FunctionDef)}
    if mode == "all":
        # class methods keyed by bare name so delegation resolves
        # (Server.search -> self.submit matches fns["submit"])
        for cls in tree.body:
            if isinstance(cls, ast.ClassDef):
                for n in cls.body:
                    if isinstance(n, ast.FunctionDef):
                        fns.setdefault(n.name, n)

    # fixed point: a function is "checked" if it calls a validator, or
    # calls a same-module function that is checked (delegation)
    checked = {name for name, fn in fns.items() if _calls_validator(fn)}
    changed = True
    while changed:
        changed = False
        for name, fn in fns.items():
            if name in checked:
                continue
            if _local_callees(fn) & checked:
                checked.add(name)
                changed = True

    try:
        path = path.relative_to(ROOT)
    except ValueError:
        pass
    return [
        f"{path}:{fn.lineno}: public entry point "
        f"'{name}' never reaches the boundary validator "
        f"(raft_tpu.integrity.boundary.check_matrix)"
        for name, fn in sorted(fns.items())
        if name in GUARDED and name not in checked
    ]


def main() -> int:
    violations = []
    for pkg, mode in PACKAGES.items():
        for path in sorted((ROOT / pkg).glob("*.py")):
            violations.extend(check_file(path, mode))
    for v in violations:
        print(v)
    if violations:
        print(f"\n{len(violations)} unguarded entry point(s); wire "
              "check_matrix/guard_nonfinite at the boundary (see "
              "docs/api.md, 'Integrity & validation').")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
