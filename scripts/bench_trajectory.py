#!/usr/bin/env python
"""Aggregate the per-round bench artifacts into one trajectory table.

The repo accumulates one ``BENCH_rNN.json`` per growth round, in two
generations of schema:

- rounds 1-5: ``{"n", "cmd", "rc", "tail", "parsed"}`` — ``parsed`` is
  the flagship metric line (``{"metric", "value", "unit",
  "vs_baseline", ...}``) and ``tail`` may hold further JSON lines;
- rounds 6+: ``{"results": [...]}`` — a heterogeneous list mixing
  flagship ``{"metric": ...}`` entries, ANN-bench-style rows
  (``{"name", "search_param", "recall", "qps", ...}``), and
  ``{"summary": "QPS at recall=0.95", ...}`` rollups.

Rounds that ran the multi-chip smoke also leave a ``MULTICHIP_rNN.json``
(``{"n_devices", "rc", "ok", "skipped", "tail"}`` — ``tail`` is the
captured stdout, which for metric-emitting legs holds the same JSON
metric lines as the bench artifacts).  Those are folded into the same
per-round row: pass/fail status plus any flagship metric parsed out of
the tail.

This script reduces each round to its headline numbers — the flagship
metric(s) and the best QPS at/above a recall floor — so the perf
history stops living only in PERFORMANCE.md prose.  Output: a markdown
table on stdout, plus the full per-round extraction as JSON with
``--json``.  CI runs it after the bench smoke and uploads the artifact.

Usage::

    python scripts/bench_trajectory.py [--dir .] [--glob 'BENCH_r*.json']
                                       [--multichip-glob 'MULTICHIP_r*.json']
                                       [--min-recall 0.95] [--json out]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional


def _round_of(path: str) -> Optional[int]:
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else None


def _json_lines(text: str) -> List[Dict[str, Any]]:
    """Parse every JSON-object line out of a captured stdout tail."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not (line.startswith("{") and line.endswith("}")):
            continue
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict):
            out.append(d)
    return out


def _entries(doc: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Flatten one round file (either schema) into result entries."""
    entries: List[Dict[str, Any]] = []
    if isinstance(doc.get("results"), list):
        entries.extend(e for e in doc["results"] if isinstance(e, dict))
    if isinstance(doc.get("parsed"), dict):
        entries.append(doc["parsed"])
    if isinstance(doc.get("tail"), str):
        for e in _json_lines(doc["tail"]):
            if e not in entries:
                entries.append(e)
    return entries


def extract_round(doc: Dict[str, Any], min_recall: float
                  ) -> Dict[str, Any]:
    """One round's headline numbers: flagship metrics + QPS@recall."""
    flagships = []
    qps_at: Optional[Dict[str, Any]] = None
    families: Dict[str, int] = {}
    for e in _entries(doc):
        if "metric" in e and "value" in e:
            flagships.append({k: e[k] for k in
                              ("metric", "value", "unit", "vs_baseline")
                              if k in e})
            continue
        if "summary" in e and "qps" in e:
            # pre-rolled "QPS at recall=X" line: trust it when its
            # floor matches ours
            m = re.search(r"recall=([\d.]+)", str(e["summary"]))
            if m and abs(float(m.group(1)) - min_recall) < 1e-9:
                cand = {"qps": float(e["qps"]),
                        "recall": float(e.get("recall", 0.0)),
                        "name": e.get("name"), "source": "summary"}
                if qps_at is None or cand["qps"] > qps_at["qps"]:
                    qps_at = cand
            continue
        if "qps" in e and "recall" in e:
            # ANN-bench row: candidate for best-QPS-at-floor
            if float(e["recall"]) >= min_recall:
                cand = {"qps": float(e["qps"]),
                        "recall": float(e["recall"]),
                        "name": e.get("name"),
                        "search_param": e.get("search_param"),
                        "source": "sweep"}
                if qps_at is None or cand["qps"] > qps_at["qps"]:
                    qps_at = cand
            continue
        # point families (overload_point, fused_windowed_point, ...):
        # counted so the table shows what each round measured
        for key in e:
            if key.endswith("_point"):
                families[key] = families.get(key, 0) + 1
    return {"flagships": flagships, "qps_at_recall": qps_at,
            "point_families": families}


def extract_multichip(doc: Dict[str, Any]) -> Dict[str, Any]:
    """One multi-chip smoke file → status + flagships from its tail."""
    flagships = []
    for e in _json_lines(doc.get("tail") or ""):
        if "metric" in e and "value" in e:
            flagships.append({k: e[k] for k in
                              ("metric", "value", "unit", "vs_baseline")
                              if k in e})
    return {"ok": bool(doc.get("ok")), "rc": doc.get("rc"),
            "skipped": bool(doc.get("skipped")),
            "n_devices": doc.get("n_devices"), "flagships": flagships}


def build_trajectory(paths: List[str], min_recall: float,
                     multichip_paths: Optional[List[str]] = None
                     ) -> List[Dict[str, Any]]:
    multichip: Dict[Optional[int], Dict[str, Any]] = {}
    for path in multichip_paths or []:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            multichip[_round_of(path)] = {"error": str(e)}
            continue
        multichip[_round_of(path)] = extract_multichip(doc)
    rounds = []
    seen: set = set()
    for path in sorted(paths, key=lambda p: (_round_of(p) or 0, p)):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            rounds.append({"round": _round_of(path), "file": path,
                           "error": str(e)})
            continue
        row = extract_round(doc, min_recall)
        row["round"] = _round_of(path)
        row["file"] = os.path.basename(path)
        if row["round"] in multichip:
            row["multichip"] = multichip[row["round"]]
            seen.add(row["round"])
        rounds.append(row)
    # multi-chip-only rounds (e.g. a chaos leg landed without a BENCH
    # artifact that round) still get a row
    for rnd in sorted(k for k in multichip if k not in seen):
        rounds.append({"round": rnd, "file": f"MULTICHIP_r{rnd:02d}.json",
                       "flagships": [], "qps_at_recall": None,
                       "point_families": {},
                       "multichip": multichip[rnd]})
    rounds.sort(key=lambda r: (r.get("round") or 0, r.get("file", "")))
    return rounds


def _fmt(v: Any) -> str:
    if isinstance(v, float):
        return f"{v:,.1f}" if abs(v) >= 100 else f"{v:.3g}"
    return str(v)


def _fmt_multichip(mc: Optional[Dict[str, Any]]) -> str:
    if not mc:
        return "—"
    if "error" in mc:
        return f"unreadable: {mc['error']}"
    if mc["skipped"]:
        return "skipped"
    status = ("ok" if mc["ok"] else f"FAIL rc={mc['rc']}")
    status += f" ({mc['n_devices']}dev)"
    if mc["flagships"]:
        f0 = mc["flagships"][0]
        status += (f" {f0.get('metric')}="
                   f"{_fmt(f0.get('value', '—'))}{f0.get('unit', '')}")
        if len(mc["flagships"]) > 1:
            status += f" (+{len(mc['flagships']) - 1} more)"
    return status


def render_table(rounds: List[Dict[str, Any]], min_recall: float) -> str:
    lines = [
        f"| round | flagship metric | value | vs_baseline "
        f"| QPS@recall>={min_recall:g} | measured | multichip |",
        "|---|---|---|---|---|---|---|",
    ]
    for r in rounds:
        if "error" in r:
            lines.append(f"| {r['round']} | (unreadable: {r['error']}) "
                         f"| | | | | |")
            continue
        flag = r["flagships"][0] if r["flagships"] else {}
        extra = (f" (+{len(r['flagships']) - 1} more)"
                 if len(r["flagships"]) > 1 else "")
        qa = r["qps_at_recall"]
        qa_s = (f"{qa['qps']:,.1f} (r={qa['recall']:.3f})" if qa else "—")
        fams = ", ".join(f"{k}×{n}"
                         for k, n in sorted(r["point_families"].items()))
        lines.append(
            f"| {r['round']} | {flag.get('metric', '—')}{extra} "
            f"| {_fmt(flag.get('value', '—'))} {flag.get('unit', '')} "
            f"| {_fmt(flag.get('vs_baseline', '—'))} "
            f"| {qa_s} | {fams or '—'} "
            f"| {_fmt_multichip(r.get('multichip'))} |")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=".",
                    help="directory holding the BENCH round files")
    ap.add_argument("--glob", default="BENCH_r*.json",
                    help="round-file glob within --dir")
    ap.add_argument("--multichip-glob", default="MULTICHIP_r*.json",
                    help="multi-chip smoke-file glob within --dir "
                         "(empty string disables the fold)")
    ap.add_argument("--min-recall", type=float, default=0.95,
                    help="recall floor for the QPS@recall column")
    ap.add_argument("--json", default=None,
                    help="also write the full extraction to this path")
    args = ap.parse_args(argv)
    paths = glob.glob(os.path.join(args.dir, args.glob))
    if not paths:
        print(f"no round files match {args.glob!r} under {args.dir!r}",
              file=sys.stderr)
        return 1
    mc_paths = (glob.glob(os.path.join(args.dir, args.multichip_glob))
                if args.multichip_glob else [])
    rounds = build_trajectory(paths, args.min_recall,
                              multichip_paths=mc_paths)
    print(render_table(rounds, args.min_recall))
    if args.json:
        with open(args.json, "w") as f:
            json.dump({"min_recall": args.min_recall, "rounds": rounds},
                      f, indent=2, sort_keys=True)
        print(f"\nwrote {args.json}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
