"""graftlint — project-wide static analysis for raft_tpu's conventions.

Eight PRs of growth made correctness rest on cross-cutting disciplines
that no single module can see violated: every mutation bumps a
generation, every ``ExecutableCache`` key carries it, every scan path
rides the ``id < 0`` padded-row/tombstone mask, no traced-shape-
dependent Python reaches the serving hot path, and every metric /
fault-site name asserted anywhere actually ticks somewhere.  The
reference (RAFT) bakes such invariants into the C++ type system; the
Python/JAX equivalent is this AST-based pass framework.

Usage::

    python -m scripts.graftlint            # human file:line:rule output
    python -m scripts.graftlint --json     # machine report + registry

Suppress a finding on one line with a reason::

    x = ids == -1  # graftlint: disable=mask-seam -- post-clamp public ids

See docs/api.md, "Static analysis" for the rule catalogue and how to
add a pass.
"""

from scripts.graftlint.core import (  # noqa: F401
    Diagnostic,
    Module,
    Project,
    all_passes,
    load_project,
    register,
    run_passes,
)
from scripts.graftlint.registry import build_registry  # noqa: F401

# importing the package registers every bundled pass
from scripts.graftlint import passes  # noqa: F401  (side-effect import)
