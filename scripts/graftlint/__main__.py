"""CLI: ``python -m scripts.graftlint [--json] [--rules a,b] [--root D]``.

Exit status 0 when the tree is clean, 1 when any diagnostic fires
(suppressed findings do not fail the run).  ``--json`` emits a machine
report including the generated metric/stage/fault-site registry, so CI
artifacts and dashboards can diff the available metric surface across
versions.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys

from scripts.graftlint import core, registry


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m scripts.graftlint",
        description="raft_tpu invariant lint (see docs/api.md, "
                    "'Static analysis')")
    parser.add_argument("--json", action="store_true",
                        help="emit a JSON report (diagnostics + "
                             "generated registry) to stdout")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule subset to run")
    parser.add_argument("--root", default=None, type=pathlib.Path,
                        help="repository root (default: autodetected)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalogue and exit")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, doc in sorted(core.rule_docs().items()):
            print(f"{rule}: {doc}")
        return 0

    rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
             if args.rules else None)
    if rules:
        unknown = set(rules) - set(core.rule_docs())
        if unknown:
            print(f"graftlint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    project = core.load_project(root=args.root)
    diags, suppressed = core.run_passes(project, rules=rules)
    reg = registry.build_registry(project)

    if args.json:
        print(json.dumps({
            "version": 1,
            "rules": core.rule_docs(),
            "diagnostics": [d.as_dict() for d in diags],
            "suppressed": suppressed,
            "registry": reg.as_dict(),
        }, indent=2, sort_keys=True))
    else:
        for d in diags:
            print(d)
    if diags:
        n = len(diags)
        print(f"\ngraftlint: {n} violation(s)"
              + (f" ({suppressed} suppressed)" if suppressed else "")
              + " — see docs/api.md 'Static analysis' for each rule's "
                "invariant and how to suppress with a reason",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # stdout piped into a pager/head that exited early — not an
        # analysis failure; silence the shutdown flush and exit clean
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        sys.exit(0)
