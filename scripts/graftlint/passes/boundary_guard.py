"""boundary-guard: public entry points must reach the boundary validator.

The PR 4 input-hardening contract: every module-level public entry
point in ``raft_tpu/neighbors`` and ``raft_tpu/cluster`` (plus class
methods on the class-shaped serving surface) that accepts user arrays
must route them through ``raft_tpu.integrity.boundary``
(``check_matrix`` / ``guard_nonfinite``) — directly, or by delegating
to a same-module function that does.  PR 4's standalone AST script
found 3 real unguarded entry points at introduction; this is that
lint, rehosted as a graftlint pass (``scripts/check_boundary_guard.py``
remains as a thin shim for back-compat).
"""

from __future__ import annotations

import ast
from typing import Dict, List

from scripts.graftlint.core import Diagnostic, Module, Project, register

# package prefix -> scan mode: "functions" checks module-level entry
# points only; "all" also checks methods of module-level classes (the
# serving surface is class-shaped: Server.submit / Server.search)
PACKAGES = {
    "raft_tpu/neighbors/": "functions",
    "raft_tpu/cluster/": "functions",
    "raft_tpu/serving/": "all",
}

# entry-point names that take user arrays and must validate them
GUARDED = {
    "build", "search", "extend", "fit", "predict", "transform",
    "fit_predict", "knn", "knn_query", "all_knn_query", "build_index",
    "eps_neighbors_l2sq", "refine", "submit", "upsert",
}
VALIDATORS = {"check_matrix", "guard_nonfinite"}


def _calls_validator(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) and node.attr in VALIDATORS:
            return True
        if isinstance(node, ast.Name) and node.id in VALIDATORS:
            return True
    return False


def _local_callees(fn: ast.FunctionDef) -> set:
    """Names a function may delegate to: direct calls, but also bare
    references (``raw(fit)(...)`` wraps ``fit`` before calling it)."""
    out = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Name):
            out.add(node.id)
        elif isinstance(node, ast.Attribute):
            out.add(node.attr)
    return out


def check_module(mod: Module, mode: str = "functions") -> List[Diagnostic]:
    tree = mod.tree
    fns: Dict[str, ast.FunctionDef] = {
        n.name: n for n in tree.body if isinstance(n, ast.FunctionDef)}
    if mode == "all":
        # class methods keyed by bare name so delegation resolves
        # (Server.search -> self.submit matches fns["submit"])
        for cls in tree.body:
            if isinstance(cls, ast.ClassDef):
                for n in cls.body:
                    if isinstance(n, ast.FunctionDef):
                        fns.setdefault(n.name, n)

    # fixed point: a function is "checked" if it calls a validator, or
    # calls a same-module function that is checked (delegation)
    checked = {name for name, fn in fns.items() if _calls_validator(fn)}
    changed = True
    while changed:
        changed = False
        for name, fn in fns.items():
            if name in checked:
                continue
            if _local_callees(fn) & checked:
                checked.add(name)
                changed = True

    return [
        Diagnostic(mod.rel, fn.lineno, "boundary-guard",
                   f"public entry point '{name}' never reaches the "
                   f"boundary validator "
                   f"(raft_tpu.integrity.boundary.check_matrix)")
        for name, fn in sorted(fns.items())
        if name in GUARDED and name not in checked
    ]


@register
class BoundaryGuardPass:
    name = "boundary-guard"
    docs = {
        "boundary-guard":
            "public build/search/extend/... entry points must route "
            "user arrays through integrity.boundary validators",
    }

    def run(self, project: Project) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for prefix, mode in PACKAGES.items():
            for mod in project.walk(prefix):
                out.extend(check_module(mod, mode))
        return out
