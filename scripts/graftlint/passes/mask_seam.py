"""mask-seam: the ``id < 0`` tombstone/padding mask must never be skipped.

Every scan formulation encodes three row states in one id array
(``neighbors/mutate``): ``>= 0`` live, ``-1`` never-filled padding,
``<= -2`` tombstoned (encoded ``-(id + 2)``).  Library code that tests
``ids == -1`` sees padding but *misses tombstones* — a delete-aware
path silently resurrects deleted rows.  The only comparisons that
respect the seam are sign tests (``< 0`` / ``>= 0``); the only place an
exact ``-1`` is legitimate is AFTER ``grouped.finalize_topk`` clamps
encoded ids to the public sentinel (suppress with a reason there).

The second seam is numeric: the fused kernels' one-hot accumulator
merges (PR 6) multiply masks into distance values — IEEE says
``0 * inf = NaN``, so sentinel distances inside ``ops/*_pallas.py``
must be the finite ``3.0e38`` (``_ACC_WORST``) wherever they can meet
a product.  An ``inf`` flowing into ``*`` / ``@`` / ``dot`` poisons
whole accumulator rows.

Rules:

- ``mask-seam``: ``== -1`` / ``!= -1`` comparisons against id-ish
  expressions (names containing ``ids`` / ``indices``, the scan id
  buffers ``outi`` / ``alli`` / ``best_i``, ``neighbors``) anywhere
  under ``raft_tpu/``.
- ``mask-seam``: a multiplication / matmul / ``dot`` in
  ``raft_tpu/ops/*_pallas.py`` with an ``inf`` literal anywhere in its
  operands.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from scripts.graftlint.core import (
    Diagnostic,
    Project,
    contains,
    register,
    terminal_name,
)

_ID_EXACT = {"outi", "alli", "best_i", "neighbors", "ti", "gi"}
_DOT_CALLS = {"dot", "dot_general", "matmul", "einsum"}


def _idish(name: str) -> bool:
    n = name.lower()
    return ("indices" in n or n in _ID_EXACT or n == "ids"
            or n.endswith("_ids") or n.startswith("ids_"))


def _idish_expr(node: ast.AST) -> Optional[str]:
    """The id-ish identifier an expression reads, if any (follows
    attribute/subscript bases: ``index.list_indices[0] == -1``)."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and _idish(node.attr):
        return node.attr
    if isinstance(node, ast.Name) and _idish(node.id):
        return node.id
    return None


def _is_minus_one(node: ast.AST) -> bool:
    return (isinstance(node, ast.UnaryOp)
            and isinstance(node.op, ast.USub)
            and isinstance(node.operand, ast.Constant)
            and node.operand.value == 1)


def _is_inf(node: ast.AST) -> bool:
    if isinstance(node, ast.Attribute) and node.attr == "inf":
        return True
    if isinstance(node, ast.Name) and node.id == "inf":
        return True
    if isinstance(node, ast.Constant) and isinstance(node.value, float):
        return node.value != node.value or abs(node.value) == float("inf")
    if (isinstance(node, ast.Call) and terminal_name(node.func) == "float"
            and node.args and isinstance(node.args[0], ast.Constant)
            and str(node.args[0].value).lower() in ("inf", "-inf",
                                                    "infinity")):
        return True
    return False


@register
class MaskSeamPass:
    name = "mask-seam"
    docs = {
        "mask-seam":
            "id arrays are masked with sign tests (tombstones are <= -2,"
            " not -1); Pallas one-hot merges need finite sentinels, "
            "never inf in a product",
    }

    def run(self, project: Project) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for mod in project.walk("raft_tpu/"):
            pallas = (mod.rel.startswith("raft_tpu/ops/")
                      and mod.rel.endswith("_pallas.py"))
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Compare):
                    self._check_compare(mod, node, out)
                if pallas:
                    if (isinstance(node, ast.BinOp)
                            and isinstance(node.op, (ast.Mult,
                                                     ast.MatMult))
                            and (contains(node.left, _is_inf)
                                 or contains(node.right, _is_inf))):
                        out.append(Diagnostic(
                            mod.rel, node.lineno, "mask-seam",
                            "inf literal flows into a product — IEEE "
                            "0*inf=NaN poisons the one-hot merge; use "
                            "the finite 3.0e38 sentinel (_ACC_WORST)"))
                    elif (isinstance(node, ast.Call)
                          and terminal_name(node.func) in _DOT_CALLS
                          and any(contains(a, _is_inf)
                                  for a in node.args)):
                        out.append(Diagnostic(
                            mod.rel, node.lineno, "mask-seam",
                            "inf literal flows into a dot/matmul — IEEE "
                            "0*inf=NaN poisons the one-hot merge; use "
                            "the finite 3.0e38 sentinel (_ACC_WORST)"))
        return out

    def _check_compare(self, mod, node: ast.Compare,
                       out: List[Diagnostic]) -> None:
        sides = [node.left] + list(node.comparators)
        ops_ok = all(isinstance(op, (ast.Eq, ast.NotEq)) for op in node.ops)
        if not ops_ok:
            return
        has_minus_one = any(_is_minus_one(s) for s in sides)
        if not has_minus_one:
            return
        for s in sides:
            name = _idish_expr(s)
            if name is not None:
                out.append(Diagnostic(
                    mod.rel, node.lineno, "mask-seam",
                    f"'{name} == -1' misses tombstones (encoded <= -2) "
                    f"— mask with a sign test (< 0 / >= 0) or clamp "
                    f"through grouped.finalize_topk first"))
                return
