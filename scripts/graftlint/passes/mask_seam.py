"""mask-seam: the ``id < 0`` tombstone/padding mask must never be skipped.

Every scan formulation encodes three row states in one id array
(``neighbors/mutate``): ``>= 0`` live, ``-1`` never-filled padding,
``<= -2`` tombstoned (encoded ``-(id + 2)``).  Library code that tests
``ids == -1`` sees padding but *misses tombstones* — a delete-aware
path silently resurrects deleted rows.  The only comparisons that
respect the seam are sign tests (``< 0`` / ``>= 0``); the only place an
exact ``-1`` is legitimate is AFTER ``grouped.finalize_topk`` clamps
encoded ids to the public sentinel (suppress with a reason there).

The second seam is numeric: the fused kernels' one-hot accumulator
merges (PR 6) multiply masks into distance values — IEEE says
``0 * inf = NaN``, so sentinel distances inside ``ops/*_pallas.py``
must be the finite ``3.0e38`` (``_ACC_WORST``) wherever they can meet
a product.  An ``inf`` flowing into ``*`` / ``@`` / ``dot`` poisons
whole accumulator rows.

The third seam is the round-14 staging ring: the windowed fused merge
parks per-step candidates in VMEM scratch (``stg_*`` / ``acc_*`` /
``*ring*`` refs) whose uncovered slots MUST hold the finite sentinel —
an ``inf`` (or any huge float that is not ``_ACC_WORST``) written into
the ring re-enters the one-hot merge as a product operand on the next
flush.  And because the merge-window selector (``ops/vmem_budget``)
and the kernel must agree on the VMEM footprint, the fused kernels'
``scratch_shapes`` must be sized by the shared budget helpers, never
by inline shape lists.

The fourth seam is the round-20 admission bit: filtered search streams
packed per-(query, candidate) admission words into the fused kernels,
which unpack them to 0/1 blocks (``adm`` / ``adm_ref`` / ``adm_words``).
The ONLY safe way to apply that bit is to fold it into the existing
validity mask (``invalid | (adm == 0)`` / ``ok & (adm > 0)``) so the
rejected candidate takes the finite ``_ACC_WORST`` sentinel exactly
like padding.  Multiplying admission bits into distances reintroduces
the ``0 * inf`` hazard AND silently turns a rejected candidate into a
zero-distance best hit; selecting with an ``inf`` branch poisons the
merge; comparing against a non-zero constant (``adm == 1``) breaks the
moment the unpack widens its nonzero encoding.

Rules:

- ``mask-seam``: ``== -1`` / ``!= -1`` comparisons against id-ish
  expressions (names containing ``ids`` / ``indices``, the scan id
  buffers ``outi`` / ``alli`` / ``best_i``, ``neighbors``) anywhere
  under ``raft_tpu/``.
- ``mask-seam``: a multiplication / matmul / ``dot`` in
  ``raft_tpu/ops/*_pallas.py`` with an ``inf`` literal anywhere in its
  operands.
- ``admission-seam``: in ``raft_tpu/ops/*_pallas.py``, an
  admission-bit expression used as a product operand, an admission
  conditional select whose branches carry an ``inf`` literal, or an
  admission bit compared against a non-zero constant.
- ``staging-ring``: a write to a staging-ring / accumulator scratch
  ref in ``raft_tpu/ops/*_pallas.py`` whose value contains an ``inf``
  literal or a non-sentinel huge-float fill.
- ``scratch-budget``: a ``scratch_shapes=`` keyword in the fused scan
  / hop kernel modules that does not route through
  ``ops.vmem_budget`` (``fused_scan_scratch`` / ``hop_scratch``; the
  legacy non-fused ``_scratch_shapes`` helper is also accepted).
"""

from __future__ import annotations

import ast
from typing import List, Optional

from scripts.graftlint.core import (
    Diagnostic,
    Project,
    contains,
    register,
    terminal_name,
)

_ID_EXACT = {"outi", "alli", "best_i", "neighbors", "ti", "gi"}
_DOT_CALLS = {"dot", "dot_general", "matmul", "einsum"}

#: modules whose kernels feed the windowed one-hot merge: their scratch
#: MUST be sized by the shared VMEM-budget helpers
_FUSED_MODULES = {
    "raft_tpu/ops/pq_group_scan_pallas.py",
    "raft_tpu/ops/pq_code_scan_pallas.py",
    "raft_tpu/ops/cagra_hop_pallas.py",
}
_SCRATCH_HELPERS = {"fused_scan_scratch", "hop_scratch",
                    "_scratch_shapes"}
_ACC_SENTINEL = 3.0e38


def _ringish(name: str) -> bool:
    n = name.lower()
    return (n.startswith("stg") or n.startswith("acc")
            or "ring" in n or "staging" in n)


def _ring_target(node: ast.AST) -> bool:
    """True for a subscripted staging-ring / accumulator scratch ref
    (``stg_v[...]``, ``acc_i[:]``, ``stg[0][:]``)."""
    if not isinstance(node, ast.Subscript):
        return False
    while isinstance(node, ast.Subscript):
        node = node.value
    return isinstance(node, ast.Name) and _ringish(node.id)


def _is_rogue_sentinel(node: ast.AST) -> bool:
    """A huge float literal that is not the shared ``_ACC_WORST``."""
    return (isinstance(node, ast.Constant)
            and isinstance(node.value, float)
            and node.value == node.value
            and abs(node.value) != float("inf")
            and abs(node.value) >= 1e30
            and abs(node.value) != _ACC_SENTINEL)


def _idish(name: str) -> bool:
    n = name.lower()
    return ("indices" in n or n in _ID_EXACT or n == "ids"
            or n.endswith("_ids") or n.startswith("ids_"))


def _idish_expr(node: ast.AST) -> Optional[str]:
    """The id-ish identifier an expression reads, if any (follows
    attribute/subscript bases: ``index.list_indices[0] == -1``)."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and _idish(node.attr):
        return node.attr
    if isinstance(node, ast.Name) and _idish(node.id):
        return node.id
    return None


def _admish(name: str) -> bool:
    n = name.lower()
    return (n == "adm" or n == "admission" or n.startswith("adm_")
            or n.endswith("_adm") or "admission" in n)


def _admish_expr(node: ast.AST) -> bool:
    """True when an expression reads an admission-bit buffer (follows
    subscript/attribute bases: ``adm_ref[0]``, ``st.adm[:, None]``)."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute):
        return _admish(node.attr)
    return isinstance(node, ast.Name) and _admish(node.id)


_SELECT_CALLS = {"where", "select", "select_n"}


def _is_minus_one(node: ast.AST) -> bool:
    return (isinstance(node, ast.UnaryOp)
            and isinstance(node.op, ast.USub)
            and isinstance(node.operand, ast.Constant)
            and node.operand.value == 1)


def _is_inf(node: ast.AST) -> bool:
    if isinstance(node, ast.Attribute) and node.attr == "inf":
        return True
    if isinstance(node, ast.Name) and node.id == "inf":
        return True
    if isinstance(node, ast.Constant) and isinstance(node.value, float):
        return node.value != node.value or abs(node.value) == float("inf")
    if (isinstance(node, ast.Call) and terminal_name(node.func) == "float"
            and node.args and isinstance(node.args[0], ast.Constant)
            and str(node.args[0].value).lower() in ("inf", "-inf",
                                                    "infinity")):
        return True
    return False


@register
class MaskSeamPass:
    name = "mask-seam"
    docs = {
        "mask-seam":
            "id arrays are masked with sign tests (tombstones are <= -2,"
            " not -1); Pallas one-hot merges need finite sentinels, "
            "never inf in a product",
        "admission-seam":
            "filtered-search admission bits fold into the validity "
            "mask and take the finite _ACC_WORST sentinel — never "
            "multiplied into distances, selected against inf, or "
            "compared to non-zero constants",
        "staging-ring":
            "windowed-merge staging rings hold the finite _ACC_WORST "
            "sentinel: no inf literals or rogue huge-float fills may "
            "reach a ring/accumulator scratch write",
        "scratch-budget":
            "fused scan/hop kernels size VMEM scratch through "
            "ops.vmem_budget helpers so the merge-window selector and "
            "the kernel agree on the footprint",
    }

    def run(self, project: Project) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for mod in project.walk("raft_tpu/"):
            pallas = (mod.rel.startswith("raft_tpu/ops/")
                      and mod.rel.endswith("_pallas.py"))
            fused_mod = mod.rel in _FUSED_MODULES
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Compare):
                    self._check_compare(mod, node, out)
                if pallas and isinstance(node, (ast.Assign, ast.AugAssign)):
                    self._check_ring_write(mod, node, out)
                if fused_mod and isinstance(node, ast.Call):
                    self._check_scratch(mod, node, out)
                if pallas:
                    self._check_admission(mod, node, out)
                    if (isinstance(node, ast.BinOp)
                            and isinstance(node.op, (ast.Mult,
                                                     ast.MatMult))
                            and (contains(node.left, _is_inf)
                                 or contains(node.right, _is_inf))):
                        out.append(Diagnostic(
                            mod.rel, node.lineno, "mask-seam",
                            "inf literal flows into a product — IEEE "
                            "0*inf=NaN poisons the one-hot merge; use "
                            "the finite 3.0e38 sentinel (_ACC_WORST)"))
                    elif (isinstance(node, ast.Call)
                          and terminal_name(node.func) in _DOT_CALLS
                          and any(contains(a, _is_inf)
                                  for a in node.args)):
                        out.append(Diagnostic(
                            mod.rel, node.lineno, "mask-seam",
                            "inf literal flows into a dot/matmul — IEEE "
                            "0*inf=NaN poisons the one-hot merge; use "
                            "the finite 3.0e38 sentinel (_ACC_WORST)"))
        return out

    def _check_compare(self, mod, node: ast.Compare,
                       out: List[Diagnostic]) -> None:
        sides = [node.left] + list(node.comparators)
        ops_ok = all(isinstance(op, (ast.Eq, ast.NotEq)) for op in node.ops)
        if not ops_ok:
            return
        has_minus_one = any(_is_minus_one(s) for s in sides)
        if not has_minus_one:
            return
        for s in sides:
            name = _idish_expr(s)
            if name is not None:
                out.append(Diagnostic(
                    mod.rel, node.lineno, "mask-seam",
                    f"'{name} == -1' misses tombstones (encoded <= -2) "
                    f"— mask with a sign test (< 0 / >= 0) or clamp "
                    f"through grouped.finalize_topk first"))
                return

    def _check_admission(self, mod, node: ast.AST,
                         out: List[Diagnostic]) -> None:
        # admission bit multiplied (or matmul'd/dotted) into a value:
        # a rejected candidate becomes distance 0 — the BEST hit — and
        # any inf partner NaN-poisons the row.  The bit is a mask, not
        # a scale factor.
        if (isinstance(node, ast.BinOp)
                and isinstance(node.op, (ast.Mult, ast.MatMult))
                and (contains(node.left, _admish_expr)
                     or contains(node.right, _admish_expr))):
            out.append(Diagnostic(
                mod.rel, node.lineno, "admission-seam",
                "admission bit used as a product operand — a rejected "
                "candidate would score 0 (the best distance!) instead "
                "of worst; fold it into the validity mask (invalid | "
                "(adm == 0)) so it takes the finite _ACC_WORST "
                "sentinel"))
            return
        if (isinstance(node, ast.Call)
                and terminal_name(node.func) in _DOT_CALLS
                and any(contains(a, _admish_expr) for a in node.args)):
            out.append(Diagnostic(
                mod.rel, node.lineno, "admission-seam",
                "admission bits flow into a dot/matmul — fold them "
                "into the validity mask and the finite _ACC_WORST "
                "sentinel, never into an accumulator product"))
            return
        # where/select on an admission condition with an inf branch:
        # the folded value must be the finite sentinel
        if (isinstance(node, ast.Call)
                and terminal_name(node.func) in _SELECT_CALLS
                and node.args
                and contains(node.args[0], _admish_expr)
                and any(contains(a, _is_inf) for a in node.args[1:])):
            out.append(Diagnostic(
                mod.rel, node.lineno, "admission-seam",
                "admission select folds rejected candidates to inf — "
                "the windowed one-hot merge multiplies masked rows "
                "(0*inf=NaN); fold to the finite 3.0e38 sentinel "
                "(_ACC_WORST) instead"))
            return
        # adm == 1 (or any non-zero constant): the unpack contract is
        # only 0 vs non-zero — test the zero side
        if isinstance(node, ast.Compare):
            sides = [node.left] + list(node.comparators)
            if not any(_admish_expr(s) for s in sides):
                return
            for s in sides:
                if (isinstance(s, ast.Constant)
                        and isinstance(s.value, (int, float))
                        and not isinstance(s.value, bool)
                        and s.value != 0):
                    out.append(Diagnostic(
                        mod.rel, node.lineno, "admission-seam",
                        "admission bit compared against a non-zero "
                        "constant — the unpack contract is 0 vs "
                        "non-zero; test '== 0' / '> 0' so a widened "
                        "encoding stays correct"))
                    return

    def _check_ring_write(self, mod, node, out: List[Diagnostic]) -> None:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        if not any(_ring_target(t) for t in targets):
            return
        if contains(node.value, _is_inf):
            out.append(Diagnostic(
                mod.rel, node.lineno, "staging-ring",
                "inf written into a staging-ring/accumulator scratch — "
                "the next windowed flush multiplies ring rows into the "
                "one-hot merge (0*inf=NaN); fill with the finite "
                "_ACC_WORST sentinel"))
        elif contains(node.value, _is_rogue_sentinel):
            out.append(Diagnostic(
                mod.rel, node.lineno, "staging-ring",
                "non-sentinel huge-float fill at a staging-ring write — "
                "uncovered ring slots must hold exactly _ACC_WORST "
                "(3.0e38) so merge liveness tests (< _ACC_WORST/2) and "
                "the epilogue agree"))

    def _check_scratch(self, mod, node: ast.Call,
                       out: List[Diagnostic]) -> None:
        for kw in node.keywords:
            if kw.arg != "scratch_shapes":
                continue
            routed = contains(
                kw.value,
                lambda n: (isinstance(n, ast.Call)
                           and terminal_name(n.func) in _SCRATCH_HELPERS))
            if not routed:
                out.append(Diagnostic(
                    mod.rel, kw.value.lineno, "scratch-budget",
                    "inline scratch_shapes in a fused kernel module — "
                    "size scratch through ops.vmem_budget "
                    "(fused_scan_scratch / hop_scratch) so the "
                    "merge-window selector and the kernel lowering "
                    "agree on the VMEM footprint"))
