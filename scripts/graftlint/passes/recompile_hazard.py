"""recompile-hazard: keep unbounded request-time shapes off the device.

The serving tier's steady-state contract (PR 5, re-asserted per round
by the serving-smoke CI job) is ZERO XLA recompiles after warmup: every
device program is compiled once per pre-warmed (bucket, k) shape, and
batch assembly / result slicing happen in host numpy.  The way that
contract erodes is one innocent line: a ``jnp`` call whose shape
derives from a request-time Python value — ``jnp.zeros((len(requests),
dim))`` compiles a fresh executable for every distinct batch size the
queue happens to cut.

Two rules, scoped to ``raft_tpu/serving/`` and ``raft_tpu/distributed/``
(the layers that sit on the request path):

- ``recompile-hazard``: a ``jnp.*`` / ``jax.*`` call with a ``len(...)``
  anywhere in its arguments.  Host-side sizing belongs in numpy; device
  shapes must come from the pre-warmed bucket constants
  (``serving.buckets``) or from index geometry fixed at build time.
- ``recompile-hazard``: a ``jax.jit`` (or bare ``jit``) call created
  inside a serving hot-path function (``search`` / ``search_bucket`` /
  ``submit`` / ``_dispatch`` / ``_run`` / ``offer`` / ``cut_batch``).
  Wrapping per request defeats the warmed-executable table; jits belong
  at module scope or in warmup/builder paths.
"""

from __future__ import annotations

import ast
from typing import List

from scripts.graftlint.core import (
    Diagnostic,
    Project,
    contains,
    dotted_name,
    register,
)

_SCOPE = ("raft_tpu/serving/", "raft_tpu/distributed/")
_DEVICE_ROOTS = ("jnp", "jax")
_HOT_FNS = {"search", "search_bucket", "submit", "_dispatch", "_run",
            "offer", "cut_batch"}


def _is_len_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "len")


@register
class RecompileHazardPass:
    name = "recompile-hazard"
    docs = {
        "recompile-hazard":
            "serving/distributed device calls must not key shapes on "
            "request-time Python sizes (len(), per-request jit)",
    }

    def run(self, project: Project) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for mod in project.walk(*_SCOPE):
            # stack of enclosing function names, for the hot-path rule
            def visit(node: ast.AST, fn_stack: tuple) -> None:
                for child in ast.iter_child_nodes(node):
                    stack = fn_stack
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        stack = fn_stack + (child.name,)
                    if isinstance(child, ast.Call):
                        self._check_call(mod, child, stack, out)
                    visit(child, stack)

            visit(mod.tree, ())
        return out

    def _check_call(self, mod, call: ast.Call, fn_stack: tuple,
                    out: List[Diagnostic]) -> None:
        target = dotted_name(call.func)
        if target is None:
            return
        root = target.split(".")[0]
        if root not in _DEVICE_ROOTS:
            return
        if target in ("jax.jit", "jit"):
            if fn_stack and (set(fn_stack) & _HOT_FNS):
                out.append(Diagnostic(
                    mod.rel, call.lineno, "recompile-hazard",
                    f"jit created inside hot-path function "
                    f"'{fn_stack[-1]}' — compile per request; hoist to "
                    f"module scope or the warmup path"))
            return
        for arg in list(call.args) + [k.value for k in call.keywords]:
            if contains(arg, _is_len_call):
                out.append(Diagnostic(
                    mod.rel, call.lineno, "recompile-hazard",
                    f"device call {target}(...) takes a len()-derived "
                    f"argument — request-time sizes retrace per novel "
                    f"shape; assemble host-side (numpy) and dispatch at "
                    f"a pre-warmed bucket shape"))
                return
