"""health-transition: shard health moves leave a trail, placements bump.

The PR 17 shard lifecycle (``raft_tpu/distributed/health.py``) promises
two things the type system can't hold:

- **Paired signals.**  Every health-state transition lands a
  ``distributed.health.*`` flight event plus the same-named counter —
  the chaos job's flight-trail gate and the failover bench both read
  them.  A code path that flips a shard's state silently (no
  ``record_event`` / ``_emit``) produces an index that routes around a
  shard nobody can see went down.
- **Generation-bumped publishes.**  A placement recompute that feeds a
  swap must advance the placement generation (the executable-cache key
  and the serving barrier both hang on it); recomputing from an
  existing placement's ``.generation`` without threading ``generation=``
  publishes a routing change old warmed executables still answer for.

Three rules, all ``health-transition``:

- a function under ``raft_tpu/distributed/`` that assigns to a
  ``*state*``-named store (attribute or subscript — the tracker's
  per-shard table) must, in the same function, call ``record_event`` or
  an ``*emit*``-named helper;
- a function under ``raft_tpu/distributed/`` or ``raft_tpu/serving/``
  that calls ``compute_placement`` *and* reads ``.generation`` off an
  existing placement must pass a ``generation=`` keyword — it is
  re-deriving a successor placement and owes the bump.  (Fresh
  placements — ``shard_by_list`` — read no generation and stay exempt.)
- **Load-score mutations go through the tracker** (PR 18): a function
  under ``raft_tpu/distributed/`` or ``raft_tpu/serving/`` that
  assigns to a ``*load_score*``-named store (the routing policy's
  per-shard score table) must, in the same function, route the
  evidence through a ``note_*``-named tracker method or emit the
  paired signal — an ad-hoc score write outside the tracker seam is a
  routing-table change no generation, event, or health state accounts
  for.
"""

from __future__ import annotations

import ast
from typing import List

from scripts.graftlint.core import (
    Diagnostic,
    Project,
    register,
    terminal_name,
    walk_functions,
)

_STATE_SCOPE = ("raft_tpu/distributed/",)
_PLACEMENT_SCOPE = ("raft_tpu/distributed/", "raft_tpu/serving/")


def _state_store(node: ast.AST):
    """The attribute/subscript target of an assignment into a
    ``*state*``-named store, or None."""
    targets = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, ast.AugAssign):
        targets = [node.target]
    for t in targets:
        base = t.value if isinstance(t, ast.Subscript) else t
        if isinstance(base, ast.Attribute) and "state" in base.attr.lower():
            return t
        if isinstance(base, ast.Name) and "state" in base.id.lower():
            return t
    return None


def _emits(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            callee = terminal_name(node.func)
            if callee is None:
                continue
            if callee == "record_event" or "emit" in callee.lower():
                return True
    return False


def _load_score_store(node: ast.AST):
    """The attribute/subscript target of an assignment into a
    ``*load_score*``-named store, or None."""
    targets = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, ast.AugAssign):
        targets = [node.target]
    for t in targets:
        base = t.value if isinstance(t, ast.Subscript) else t
        if (isinstance(base, ast.Attribute)
                and "load_score" in base.attr.lower()):
            return t
        if isinstance(base, ast.Name) and "load_score" in base.id.lower():
            return t
    return None


def _routes_through_tracker(fn: ast.AST) -> bool:
    """A ``note_*``-named call (the tracker's evidence seam) anywhere
    in the function — the overload demotion path."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            callee = terminal_name(node.func)
            if callee is not None and callee.startswith("note_"):
                return True
    return False


def _reads_placement_generation(fn: ast.AST) -> bool:
    """``<something>.generation`` read anywhere in the function where
    the base mentions a placement (``placement.generation``,
    ``index.placement.generation``, ...)."""
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Attribute)
                and node.attr == "generation"):
            continue
        base = node.value
        if (isinstance(base, ast.Attribute)
                and "placement" in base.attr.lower()):
            return True
        if isinstance(base, ast.Name) and "placement" in base.id.lower():
            return True
    return False


@register
class HealthTransitionPass:
    name = "health-transition"
    docs = {
        "health-transition":
            "shard health-state mutations must emit the paired flight "
            "event + counter; placement recomputes derived from an "
            "existing placement must thread the generation bump",
    }

    def run(self, project: Project) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for mod in project.walk(*_STATE_SCOPE):
            for fn, _stack in walk_functions(mod.tree):
                store = None
                for node in ast.walk(fn):
                    store = _state_store(node)
                    if store is not None:
                        lineno = node.lineno
                        break
                if store is None:
                    continue
                if _emits(fn):
                    continue
                out.append(Diagnostic(
                    mod.rel, lineno, "health-transition",
                    f"'{fn.name}' mutates shard health state without a "
                    f"paired signal — every transition must land a "
                    f"distributed.health.* flight event + counter "
                    f"(call record_event or the module's _emit helper) "
                    f"or the chaos flight-trail gate goes blind"))
        for mod in project.walk(*_PLACEMENT_SCOPE):
            for fn, _stack in walk_functions(mod.tree):
                store = None
                for node in ast.walk(fn):
                    store = _load_score_store(node)
                    if store is not None:
                        lineno = node.lineno
                        break
                if store is not None and not (_routes_through_tracker(fn)
                                              or _emits(fn)):
                    out.append(Diagnostic(
                        mod.rel, lineno, "health-transition",
                        f"'{fn.name}' mutates a routing load score "
                        f"outside the tracker seam — overload evidence "
                        f"must go through a note_* tracker method (or "
                        f"emit the paired signal); an ad-hoc score "
                        f"write is a routing change nothing accounts "
                        f"for"))
        for mod in project.walk(*_PLACEMENT_SCOPE):
            for fn, _stack in walk_functions(mod.tree):
                call = None
                for node in ast.walk(fn):
                    if (isinstance(node, ast.Call)
                            and terminal_name(node.func)
                            == "compute_placement"):
                        call = node
                        break
                if call is None or fn.name == "compute_placement":
                    continue
                if not _reads_placement_generation(fn):
                    continue  # fresh placement — no predecessor to bump
                if any(kw.arg == "generation" for kw in call.keywords):
                    continue
                out.append(Diagnostic(
                    mod.rel, call.lineno, "health-transition",
                    f"'{fn.name}' recomputes a placement derived from "
                    f"an existing one without passing generation= — a "
                    f"published routing change outside a generation "
                    f"bump lets stale warmed executables answer for "
                    f"the old placement"))
        return out
