"""raw-perf-counter / bare-sleep: timing and waiting have one home each.

Library wall-timing must go through ``observability.stage()`` so it is
fenced (device work actually finished), labeled, aggregated, and
collection-gated — a raw ``time.perf_counter()`` pair measures dispatch
time and exports nothing.  Sleeping belongs to the resilience
retry/backoff layer only: a bare ``time.sleep()`` anywhere else hides
latency from the latency histograms and breaks ``Deadline`` accounting
(a deadline cannot preempt a sleep it does not know about).

These were CI ``grep`` steps through PR 8; as greps they false-
positived on comments, docstrings and this very file's documentation.
As AST passes they flag only the actual attribute load / call:

- ``raw-perf-counter``: any use of ``time.perf_counter`` under
  ``raft_tpu/`` outside ``raft_tpu/observability/``
  (``time.monotonic`` stays legal — deadlines/batch cuts are control
  flow, not telemetry).
- ``bare-sleep``: any ``time.sleep(...)`` call under ``raft_tpu/``
  outside ``raft_tpu/resilience/`` (``cond.wait(timeout=...)`` and
  friends stay legal — they are wakeable).
"""

from __future__ import annotations

import ast
from typing import List

from scripts.graftlint.core import (
    Diagnostic,
    Project,
    import_aliases,
    register,
)


@register
class TimingDisciplinePass:
    name = "timing-discipline"
    docs = {
        "raw-perf-counter":
            "library timing goes through observability.stage(), not raw "
            "time.perf_counter()",
        "bare-sleep":
            "waits go through resilience.retry backoff, not bare "
            "time.sleep()",
    }

    def run(self, project: Project) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for mod in project.walk("raft_tpu/"):
            aliases = import_aliases(mod.tree)
            time_names = {local for local, full in aliases.items()
                          if full == "time"}
            check_pc = not mod.in_dir("raft_tpu/observability/")
            check_sleep = not mod.in_dir("raft_tpu/resilience/")
            for node in ast.walk(mod.tree):
                if check_pc and self._is_time_member(
                        node, aliases, time_names, "perf_counter"):
                    out.append(Diagnostic(
                        mod.rel, node.lineno, "raw-perf-counter",
                        "raw time.perf_counter() in library code — use "
                        "raft_tpu.observability.stage() so the timing "
                        "is fenced, labeled and exported"))
                if (check_sleep and isinstance(node, ast.Call)
                        and self._is_time_member(
                            node.func, aliases, time_names, "sleep")):
                    out.append(Diagnostic(
                        mod.rel, node.lineno, "bare-sleep",
                        "bare time.sleep() in library code — route "
                        "waits through raft_tpu.resilience.retry so "
                        "deadlines can account for them"))
        return out

    @staticmethod
    def _is_time_member(node: ast.AST, aliases, time_names,
                        member: str) -> bool:
        if (isinstance(node, ast.Attribute) and node.attr == member
                and isinstance(node.value, ast.Name)
                and node.value.id in (time_names or {"time"})):
            return True
        if (isinstance(node, ast.Name)
                and aliases.get(node.id) == f"time.{member}"):
            return True
        return False
