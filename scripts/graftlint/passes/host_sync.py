"""host-sync: the steady-state dispatch must not read device values back.

Round 10's serving contract is ZERO host syncs on the warmed dispatch
path: group construction is shape-static (a static group capacity rides
in the compiled shape), so nothing about a batch needs to come back to
Python before the next dispatch.  The way that contract erodes is one
innocent readback — ``int(jnp.max(...))`` to size a buffer,
``np.asarray(device_result)`` to "just look at" a value — each of which
stalls the dispatch thread on device completion and reintroduces the
per-batch sync round 10 removed.

One rule, scoped to the same serving/distributed hot-path functions as
``recompile-hazard`` (``search`` / ``search_bucket`` / ``submit`` /
``_dispatch`` / ``_run`` / ``offer`` / ``cut_batch``):

- ``host-sync``: ``int(x)`` / ``float(x)`` / ``np.asarray(x)`` /
  ``np.array(x)`` where ``x`` mentions a ``jnp.`` / ``jax.`` call or a
  local name assigned from a non-numpy call (conservatively a device
  value in these functions), and any ``.block_until_ready()`` call.

Legitimate readbacks exist — the batcher's single result readback that
feeds request futures, the calibrated-capacity overflow gate that
triggers the exact re-dispatch — and each one must carry a reasoned
per-line suppression (``# graftlint: disable=host-sync -- why``) so the
set of sync points stays enumerable in one grep.
"""

from __future__ import annotations

import ast
from typing import List, Set

from scripts.graftlint.core import (
    Diagnostic,
    Project,
    contains,
    dotted_name,
    register,
)

# same request-path scope + hot-function set as recompile-hazard: the
# two passes guard the two halves of the steady-state contract (no
# recompiles, no syncs) over the same code
from scripts.graftlint.passes.recompile_hazard import _HOT_FNS, _SCOPE

_DEVICE_ROOTS = ("jnp", "jax")
# call roots whose results are host values, never device arrays
_HOST_ROOTS = {"np", "numpy", "math", "time", "os", "re", "warnings",
               "int", "float", "str", "bool", "len", "range", "sum",
               "min", "max", "abs", "sorted", "list", "tuple", "dict",
               "set", "enumerate", "zip", "isinstance", "getattr",
               "hasattr", "print", "bucket_for", "valid_rows_mask"}
_COERCIONS = {"int", "float"}
_METADATA = {"shape", "ndim", "size", "dtype", "sharding"}
_ASARRAY = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}


def _is_device_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    target = dotted_name(node.func)
    return (target is not None
            and target.split(".")[0] in _DEVICE_ROOTS)


def _taints(value: ast.AST) -> bool:
    """Does assigning from this expression make the target plausibly a
    device array?  jnp/jax-rooted calls do; so does any call whose root
    is not a known host namespace (dispatch closures, executor methods —
    in a hot-path function their results are device arrays until the
    explicit readback)."""
    if contains(value, _is_device_call):
        return True
    if isinstance(value, ast.Tuple):
        return any(_taints(e) for e in value.elts)
    if isinstance(value, ast.Call):
        target = dotted_name(value.func)
        if target is None:   # method on a subscript/call result etc.
            return True
        return target.split(".")[0] not in _HOST_ROOTS
    return False


def _target_names(target: ast.AST) -> List[str]:
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for e in target.elts:
            out.extend(_target_names(e))
        return out
    return []


@register
class HostSyncPass:
    name = "host-sync"
    docs = {
        "host-sync":
            "serving/distributed hot-path functions must not read device "
            "values back to the host (int()/np.asarray()/"
            "block_until_ready on device results)",
    }

    def run(self, project: Project) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for mod in project.walk(*_SCOPE):
            for fn, stack in self._hot_functions(mod.tree):
                self._check_fn(mod, fn, out)
        return out

    def _hot_functions(self, tree: ast.AST):
        def visit(node, stack):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    names = stack + (child.name,)
                    if set(names) & _HOT_FNS:
                        yield child, names
                    yield from visit(child, names)
                else:
                    yield from visit(child, stack)
        yield from visit(tree, ())

    def _check_fn(self, mod, fn, out: List[Diagnostic]) -> None:
        tainted: Set[str] = set()

        def device_ref(node: ast.AST) -> bool:
            # .shape / .ndim / .dtype of a device array are static
            # trace-time metadata, not value readbacks — prune the whole
            # subtree so int(x.shape[0]) never flags
            if (isinstance(node, ast.Attribute)
                    and node.attr in _METADATA):
                return False
            if _is_device_call(node):
                return True
            if isinstance(node, ast.Name) and node.id in tainted:
                return True
            return any(device_ref(c) for c in ast.iter_child_nodes(node))

        def check_expr(node: ast.AST) -> None:
            for call in (n for n in ast.walk(node)
                         if isinstance(n, ast.Call)):
                target = dotted_name(call.func)
                term = (call.func.attr
                        if isinstance(call.func, ast.Attribute) else None)
                if (term == "block_until_ready"
                        or target == "jax.block_until_ready"):
                    out.append(Diagnostic(
                        mod.rel, call.lineno, "host-sync",
                        f"block_until_ready in hot-path function "
                        f"'{fn.name}' — blocks the dispatch thread on "
                        f"device completion; belongs in warmup/bench "
                        f"paths only"))
                    continue
                if not call.args:
                    continue
                sink = None
                if target in _COERCIONS:
                    sink = f"{target}()"
                elif target in _ASARRAY:
                    sink = f"{target}()"
                if sink and device_ref(call.args[0]):
                    out.append(Diagnostic(
                        mod.rel, call.lineno, "host-sync",
                        f"{sink} of a device value in hot-path function "
                        f"'{fn.name}' — per-batch readback stalls the "
                        f"steady-state dispatch; keep it in-graph, or "
                        f"suppress with a reason if this readback is "
                        f"the documented sync point"))

        def walk_stmts(stmts) -> None:
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue  # nested fns get their own taint scope
                if isinstance(stmt, ast.Assign):
                    check_expr(stmt.value)
                    names = [n for t in stmt.targets
                             for n in _target_names(t)]
                    if _taints(stmt.value):
                        tainted.update(names)
                    else:
                        tainted.difference_update(names)
                elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
                    if stmt.value is not None:
                        check_expr(stmt.value)
                        names = _target_names(stmt.target)
                        if _taints(stmt.value):
                            tainted.update(names)
                else:
                    for field in ast.iter_child_nodes(stmt):
                        if isinstance(field, ast.stmt):
                            continue
                        if isinstance(field, ast.withitem):
                            check_expr(field.context_expr)
                        elif isinstance(field, ast.expr):
                            check_expr(field)
                # recurse into compound-statement bodies in source order
                for name in ("body", "orelse", "finalbody", "handlers"):
                    sub = getattr(stmt, name, None)
                    if not sub:
                        continue
                    if name == "handlers":
                        for h in sub:
                            walk_stmts(h.body)
                    else:
                        walk_stmts(sub)

        walk_stmts(fn.body)
