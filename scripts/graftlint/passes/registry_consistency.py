"""registry-consistency: every asserted metric / fault-site name ticks.

The bug class: a test asserts ``snap["counters"]["serving.admited"]
== 3`` (typo), ``.get(...)`` quietly returns 0, the assertion is
rewritten to ``>= 0`` in a hurry, and the counter is dead forever.
Same shape for fault sites: ``plan.at("rebalance.swop")`` scripts a
failure no ``maybe_fail`` will ever fire, and the resilience test
passes vacuously.

This pass checks every *reference* against the registry generated from
the library AST (:mod:`scripts.graftlint.registry`).  References are
collected from anchored contexts only — arbitrary dotted strings are
not guessed at:

- ``.counter("…") / .gauge("…") / .timer("…") / .histogram("…")`` calls
  (in ``tests/`` these are reads of names the library must define);
- ``snapshot()["counters"]["…"]`` subscripts, ``["…"] .get(…)`` calls
  and ``"…" in snap["timers"]`` membership tests;
- ``plan.at("…")`` / ``inject("…")`` fault-site scripting calls;
- ``flight.events("…")`` filters and ``record_event("…")`` calls
  (anomaly event names — a typo'd filter matches nothing forever);
- ``span("…")`` / ``SpanRecorder("…")`` / ``start_request("…")`` calls
  (trace-span names; stage labels resolve as spans too, since
  ``stage()`` mirrors its timing onto the ambient trace).

A name is only policed when its first dotted segment is a namespace
root the registry knows (``serving.``, ``integrity.``, ``comms.``, …)
— synthetic unit-test names (``"c"``, ``"site.a"``) fall outside the
roots and are skipped.  Dynamic library names (``f"comms.{op}.calls"``)
resolve by prefix.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from scripts.graftlint.core import (
    Diagnostic,
    Project,
    register,
    str_const,
    terminal_name,
)
from scripts.graftlint.registry import build_registry

_METRIC_CALLS = {"counter", "gauge", "timer", "histogram"}
_SNAPSHOT_KINDS = {"counters", "gauges", "timers", "histograms"}
_SITE_CALLS = {"at", "inject", "maybe_fail"}
_EVENT_CALLS = {"events", "record_event"}
_SPAN_CALLS = {"span", "SpanRecorder", "start_request"}


def _snapshot_kind(node: ast.AST) -> Optional[str]:
    """``"counters"`` for an expression like ``snap["counters"]``."""
    if isinstance(node, ast.Subscript):
        kind = str_const(node.slice)
        if kind in _SNAPSHOT_KINDS:
            return kind
    return None


@register
class RegistryConsistencyPass:
    name = "registry-consistency"
    docs = {
        "registry-consistency":
            "metric / stage / fault-site names referenced in raft_tpu/ "
            "or asserted in tests/ must resolve against the generated "
            "registry (typo'd counters never tick)",
    }

    def run(self, project: Project) -> List[Diagnostic]:
        reg = build_registry(project)
        roots = reg.roots()
        out: List[Diagnostic] = []
        checks = {
            "metric": (reg.resolves_metric,
                       "metric '{0}' is never recorded by raft_tpu/ — "
                       "a typo'd name reads 0 forever"),
            "site": (reg.resolves_site,
                     "fault site '{0}' matches no maybe_fail() site in "
                     "raft_tpu/ — the scripted failure can never fire"),
            "event": (reg.resolves_event,
                      "flight event '{0}' is never recorded by "
                      "raft_tpu/ — a typo'd filter matches nothing"),
            "span": (reg.resolves_span,
                     "span '{0}' matches no span or stage recorded by "
                     "raft_tpu/ — a typo'd span name never appears in "
                     "a trace"),
        }
        for mod in project.walk("raft_tpu/", "tests/"):
            for name, line, kind in self._references(mod):
                if "." not in name or name.split(".")[0] not in roots:
                    continue
                resolves, msg = checks[kind]
                if not resolves(name):
                    out.append(Diagnostic(
                        mod.rel, line, "registry-consistency",
                        msg.format(name)))
        return out

    def _references(self, mod) -> List[Tuple[str, int, str]]:
        refs: List[Tuple[str, int, str]] = []

        def add(name: Optional[str], line: int, kind: str) -> None:
            if name:
                refs.append((name, line, kind))

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                callee = terminal_name(node.func)
                if callee in _METRIC_CALLS and node.args:
                    add(str_const(node.args[0]), node.lineno, "metric")
                elif callee in _SITE_CALLS and node.args:
                    add(str_const(node.args[0]), node.lineno, "site")
                elif callee in _EVENT_CALLS and node.args:
                    add(str_const(node.args[0]), node.lineno, "event")
                elif callee in _SPAN_CALLS and node.args:
                    add(str_const(node.args[0]), node.lineno, "span")
                elif (callee == "get" and node.args
                      and isinstance(node.func, ast.Attribute)
                      and _snapshot_kind(node.func.value)):
                    add(str_const(node.args[0]), node.lineno, "metric")
            elif isinstance(node, ast.Subscript):
                if _snapshot_kind(node.value):
                    add(str_const(node.slice), node.lineno, "metric")
            elif isinstance(node, ast.Compare):
                # "name" in snap["timers"]
                if (len(node.ops) == 1
                        and isinstance(node.ops[0], (ast.In, ast.NotIn))
                        and _snapshot_kind(node.comparators[0])):
                    add(str_const(node.left), node.lineno, "metric")
        return refs
