"""Bundled graftlint passes.  Importing this package registers them."""

from scripts.graftlint.passes import (  # noqa: F401
    boundary_guard,
    generation_discipline,
    health_transition,
    host_sync,
    mask_seam,
    recompile_hazard,
    registry_consistency,
    timing_discipline,
)
