"""generation-discipline: mutations bump, caches key, nobody forgets.

The mutation layer's snapshot model (PR 7) hangs on one host-side
integer: every ``delete`` / ``extend`` / ``compact`` / ``upsert``
returns a NEW index object stamped ``parent.generation + 1``
(``neighbors/mutate.next_generation``), and the serving tier's
``ExecutableCache`` keys every warmed executable on that counter (plus
the placement generation for routed indexes).  Forget either side and
the failure is silent: a forgotten bump lets a recycled ``id()`` serve
a *stale executable* for a mutated index; a key-site without the
generation re-introduces the bucket-collision bug the weakref guard
was built to kill.

Two rules:

- ``generation-discipline``: a function under ``raft_tpu/neighbors/``,
  ``raft_tpu/serving/`` or ``raft_tpu/distributed/`` that takes an
  existing index (parameter named ``index`` / ``parent``) and
  constructs a new one (a ``*Index(...)`` constructor or
  ``dataclasses.replace``) must bump or propagate the generation:
  call ``next_generation``, assign ``.generation``, or read
  ``mutate.generation(...)``.
- ``generation-discipline``: inside any class with ``Cache`` in its
  name, an assignment to a variable named ``key`` must mention the
  generation (a ``generation`` name/attribute or a ``"generation"``
  string, e.g. via ``getattr``) — every executable-cache key carries
  the generation, and routed paths additionally carry the placement
  generation.
- ``generation-discipline`` (fold publishing, PR 13): a serving-layer
  function with ``fold`` in its name — the LSM compaction folding the
  streaming-ingest memtable into the main index — that derives a new
  index (``delete`` / ``extend`` / ``upsert`` / ``compact`` /
  ``replace`` / an ``*Index`` constructor) must publish it through
  ``swap_index`` or a generation bump, and must NEVER assign to a
  published index's array leaves (``list_data``, ``centers``, …) in
  place: in-flight readers pinned on the old generation would observe
  the mutation mid-scan.
- ``generation-discipline`` (shard-local folds, round 19): a
  serving-layer fold that drains the ROUTED tier's per-shard memtables
  — recognizable because the function mentions the placement — must
  additionally thread the PLACEMENT generation (read
  ``<placement>.generation``, e.g. ``placement.generation + 1`` into
  ``compute_placement``): the executable cache keys routed programs on
  the placement generation, so a per-shard drain republished under the
  same placement generation serves stale routing tables.
"""

from __future__ import annotations

import ast
from typing import List

from scripts.graftlint.core import (
    Diagnostic,
    Project,
    register,
    terminal_name,
    walk_functions,
)

_SCOPE = ("raft_tpu/neighbors/", "raft_tpu/serving/",
          "raft_tpu/distributed/")
_PARENT_PARAMS = {"index", "parent"}

#: the array leaves of the index dataclasses — a fold writing any of
#: these on an existing object is mutating a (potentially published)
#: generation in place instead of building a candidate and swapping
_INDEX_LEAF_ATTRS = {
    "list_data", "list_indices", "list_sizes", "list_data_sq",
    "centers", "codebooks", "list_codes", "list_recon", "rotation",
    "dataset", "graph",
}

#: calls that DERIVE a new index from an existing one (snapshot
#: mutations) — a fold touching these owes a publish
_DERIVING_CALLS = {"delete", "extend", "upsert", "compact"}


def _constructs_index(fn: ast.AST):
    """First Call node in ``fn`` that builds an index-like object."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        callee = terminal_name(node.func)
        if callee is None:
            continue
        if callee == "Index" or callee.endswith("Index"):
            return node
        if callee == "replace" and node.args:
            first = node.args[0]
            if isinstance(first, ast.Name) and first.id in _PARENT_PARAMS:
                return node
    return None


def _handles_generation(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            callee = terminal_name(node.func)
            if callee in ("next_generation", "generation"):
                return True
        # out.generation = ... (direct stamp, e.g. deserializers)
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, ast.Attribute) and t.attr == "generation":
                    return True
    return False


def _derives_index(fn: ast.AST):
    """First Call node applying a snapshot mutation (delete/extend/...)
    — evidence the function produces a new index generation."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            callee = terminal_name(node.func)
            if callee in _DERIVING_CALLS:
                return node
    return None


def _calls_name(fn: ast.AST, name: str) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            if terminal_name(node.func) == name:
                return True
    return False


def _params(fn: ast.AST) -> set:
    a = fn.args
    return {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}


def _mentions_placement(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and "placement" in n.id:
            return True
        if isinstance(n, ast.Attribute) and "placement" in n.attr:
            return True
        if isinstance(n, ast.keyword) and n.arg and "placement" in n.arg:
            return True
    return False


def _threads_placement_generation(fn: ast.AST) -> bool:
    """True when ``fn`` reads ``<placement-ish>.generation`` — the
    evidence a shard-local fold derives the NEXT placement generation
    from the current one instead of republishing under the same one."""
    for node in ast.walk(fn):
        if (isinstance(node, ast.Attribute) and node.attr == "generation"
                and _mentions_placement(node.value)):
            return True
    return False


def _mentions_generation(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and "generation" in n.id:
            return True
        if isinstance(n, ast.Attribute) and "generation" in n.attr:
            return True
        if (isinstance(n, ast.Constant) and isinstance(n.value, str)
                and "generation" in n.value):
            return True
    return False


@register
class GenerationDisciplinePass:
    name = "generation-discipline"
    docs = {
        "generation-discipline":
            "index-from-index constructors must bump/propagate the "
            "generation; executable-cache keys must include it",
    }

    def run(self, project: Project) -> List[Diagnostic]:
        out: List[Diagnostic] = []
        for mod in project.walk(*_SCOPE):
            for fn, stack in walk_functions(mod.tree):
                if not (_params(fn) & _PARENT_PARAMS):
                    continue
                # only the outermost such function is accountable —
                # nested helpers inherit the parent's bump
                if any(_params(f) & _PARENT_PARAMS for f in stack):
                    continue
                ctor = _constructs_index(fn)
                if ctor is None:
                    continue
                if _handles_generation(fn):
                    continue
                out.append(Diagnostic(
                    mod.rel, ctor.lineno, "generation-discipline",
                    f"'{fn.name}' builds a new index from an existing "
                    f"one without bumping/propagating the generation "
                    f"(call mutate.next_generation or assign "
                    f".generation) — stale warmed executables otherwise"))
        # cache-key rule: core/aot.py plus any serving-layer cache
        for mod in project.walk("raft_tpu/"):
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                if "Cache" not in node.name:
                    continue
                for sub in ast.walk(node):
                    if not isinstance(sub, ast.Assign):
                        continue
                    named_key = any(
                        isinstance(t, ast.Name) and t.id == "key"
                        for t in sub.targets)
                    if not named_key:
                        continue
                    if _mentions_generation(sub.value):
                        continue
                    out.append(Diagnostic(
                        mod.rel, sub.lineno, "generation-discipline",
                        f"cache key in {node.name} does not include the "
                        f"index generation — a recycled id() can pair a "
                        f"stale executable with a newer generation"))
        # fold-publishing rule: serving-layer folds (the streaming-ingest
        # memtable compaction) publish candidates, never mutate in place
        for mod in project.walk("raft_tpu/serving/"):
            for fn, stack in walk_functions(mod.tree):
                if "fold" not in fn.name.lower():
                    continue
                for node in ast.walk(fn):
                    if not isinstance(node, (ast.Assign, ast.AugAssign)):
                        continue
                    targets = (node.targets if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        if (isinstance(t, ast.Attribute)
                                and t.attr in _INDEX_LEAF_ATTRS):
                            out.append(Diagnostic(
                                mod.rel, node.lineno,
                                "generation-discipline",
                                f"'{fn.name}' writes index leaf "
                                f"'.{t.attr}' in place — a fold must "
                                f"build a candidate and publish via "
                                f"swap_index; in-flight readers pinned "
                                f"on the old generation would observe "
                                f"the mutation mid-scan"))
                deriver = _constructs_index(fn) or _derives_index(fn)
                if deriver is None:
                    continue
                if (_handles_generation(fn)
                        or _calls_name(fn, "swap_index")):
                    continue
                out.append(Diagnostic(
                    mod.rel, deriver.lineno, "generation-discipline",
                    f"'{fn.name}' folds into a new index without "
                    f"publishing it — route the candidate through "
                    f"swap_index (or bump .generation) so warmed "
                    f"executables never alias a stale generation"))
        # shard-local fold rule (round 19): a serving fold that drains
        # the routed tier's per-shard memtables (it mentions the
        # placement) must thread the PLACEMENT generation too — routed
        # executables are keyed on it, so a drain republished under the
        # same placement generation serves stale routing tables
        for mod in project.walk("raft_tpu/serving/"):
            for fn, stack in walk_functions(mod.tree):
                if "fold" not in fn.name.lower():
                    continue
                deriver = _constructs_index(fn) or _derives_index(fn)
                if deriver is None:
                    continue
                if not _mentions_placement(fn):
                    continue
                if _threads_placement_generation(fn):
                    continue
                out.append(Diagnostic(
                    mod.rel, deriver.lineno, "generation-discipline",
                    f"'{fn.name}' drains shard-local memtables without "
                    f"threading the placement generation — derive the "
                    f"published placement from "
                    f"'<placement>.generation + 1' so routed "
                    f"executable-cache keys advance with the drain"))
        return out
