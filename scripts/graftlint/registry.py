"""Generated metric / stage / fault-site registry.

The observability and resilience layers are *name-coupled*: library
code ticks ``obs.registry().counter("serving.admitted")`` and a test
(or dashboard) asserts the same string.  Nothing checks the two sides
agree — a typo'd counter silently reads 0 forever (the
counter-never-ticks bug class).  This module derives the authoritative
name sets **from the library AST at lint time** instead of a
hand-maintained list:

- **metrics** — every literal (or literal-prefixed f-string / string
  concat) first argument to ``.counter(...)`` / ``.gauge(...)`` /
  ``.timer(...)`` / ``.histogram(...)`` under ``raft_tpu/``;
- **stages** — every ``stage("...")`` label (stage labels become timer
  names on exit);
- **fault sites** — every ``maybe_fail("...")`` site;
- **spans** — every ``span("...")`` / ``SpanRecorder("...")`` /
  ``start_request("...")`` trace-span name (stage labels also resolve as
  spans: ``stage()`` mirrors its timing onto the ambient trace);
- **events** — every ``record_event("...")`` flight-recorder anomaly
  name.

Dynamic names resolve one level of indirection: when the name argument
is a bare parameter of the enclosing function (the ``_count(name)``
helper idiom), the extractor collects the literal arguments of every
same-module call to that function — so ``_count("serving.shed.deadline")``
defines ``serving.shed.deadline``, and ``_entry("distributed.ann.build",
...)`` defines the ``distributed.ann.build`` fault site fired by the
``maybe_fail(site)`` inside ``_entry``.

``python -m scripts.graftlint --json`` emits the registry in its
report so dashboards can diff the available metric surface across
versions.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from scripts.graftlint.core import (
    Project,
    str_const,
    terminal_name,
)

_METRIC_KINDS = ("counter", "gauge", "timer", "histogram")
_ALL_KINDS = _METRIC_KINDS + ("stage", "fault_site", "span", "event")


@dataclasses.dataclass
class Registry:
    """Exact names and f-string prefixes per kind.  ``kind`` is one of
    the metric kinds, ``"stage"``, ``"fault_site"``, ``"span"`` or
    ``"event"``."""

    names: Dict[str, Set[str]] = dataclasses.field(
        default_factory=lambda: {k: set() for k in _ALL_KINDS})
    prefixes: Dict[str, Set[str]] = dataclasses.field(
        default_factory=lambda: {k: set() for k in _ALL_KINDS})

    def add(self, kind: str, name: Optional[str], prefix: Optional[str]
            ) -> None:
        if name:
            self.names[kind].add(name)
        elif prefix:
            self.prefixes[kind].add(prefix)

    # -- resolution --------------------------------------------------------

    def metric_names(self) -> Set[str]:
        """Every name a metric read could legitimately use: counters,
        gauges, timers, histograms, plus stage labels (stages surface as
        timers in snapshots)."""
        out: Set[str] = set()
        for k in _METRIC_KINDS + ("stage",):
            out |= self.names[k]
        return out

    def metric_prefixes(self) -> Set[str]:
        out: Set[str] = set()
        for k in _METRIC_KINDS + ("stage",):
            out |= self.prefixes[k]
        return out

    def roots(self) -> Set[str]:
        """First dotted segments of every known name/prefix — the
        namespace the consistency pass polices.  Dotted strings outside
        these roots (test-synthetic sites like ``site.a``, module paths)
        are not metric references and are skipped."""
        out = set()
        for names in self.names.values():
            out |= {n.split(".")[0] for n in names if "." in n}
        for prefixes in self.prefixes.values():
            out |= {p.split(".")[0] for p in prefixes if "." in p}
        return out

    def resolves_metric(self, name: str) -> bool:
        if name in self.metric_names():
            return True
        return any(name.startswith(p) for p in self.metric_prefixes())

    def resolves_site(self, site: str) -> bool:
        if site in self.names["fault_site"]:
            return True
        return any(site.startswith(p)
                   for p in self.prefixes["fault_site"])

    def resolves_event(self, name: str) -> bool:
        if name in self.names["event"]:
            return True
        return any(name.startswith(p) for p in self.prefixes["event"])

    def resolves_span(self, name: str) -> bool:
        """Span names include stage labels: ``stage()`` mirrors its timing
        as a span under the same label (trace.stage_hook)."""
        if name in self.names["span"] or name in self.names["stage"]:
            return True
        return any(name.startswith(p)
                   for p in self.prefixes["span"] | self.prefixes["stage"])

    def as_dict(self) -> Dict[str, object]:
        return {
            "counters": sorted(self.names["counter"]),
            "gauges": sorted(self.names["gauge"]),
            "timers": sorted(self.names["timer"]),
            "histograms": sorted(self.names["histogram"]),
            "stages": sorted(self.names["stage"]),
            "fault_sites": sorted(self.names["fault_site"]),
            "spans": sorted(self.names["span"]),
            "events": sorted(self.names["event"]),
            "prefixes": {k: sorted(v) for k, v in self.prefixes.items()
                         if v},
        }


def _literal_or_prefix(node: ast.AST
                       ) -> Tuple[Optional[str], Optional[str]]:
    """Classify a name-argument expression: ``("lit", None)`` for a
    string constant, ``(None, "pre.")`` for an f-string / concat with a
    literal head, ``(None, None)`` otherwise."""
    s = str_const(node)
    if s is not None:
        return s, None
    if isinstance(node, ast.JoinedStr):
        head = ""
        for part in node.values:
            p = str_const(part)
            if p is None:
                break
            head += p
        return None, head or None
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        head = str_const(node.left)
        if head is not None:
            return None, head
    return None, None


def _param_index(fn: ast.AST, name: str) -> Optional[int]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args]
    if name in names:
        return names.index(name)
    return None


def _param_default(fn: ast.AST, pos: int) -> Optional[ast.AST]:
    """The default-value expression of positional parameter ``pos``, if
    any — ``start_request(name="serving.request")`` defines the root span
    name through its default, not a call site."""
    args = fn.args
    params = args.posonlyargs + args.args
    first_with_default = len(params) - len(args.defaults)
    if pos >= first_with_default:
        return args.defaults[pos - first_with_default]
    return None


def _calls_of(tree: ast.AST, fname: str) -> List[ast.Call]:
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and terminal_name(node.func) == fname:
            out.append(node)
    return out


def _enclosing_chains(tree: ast.AST) -> Dict[int, Tuple[ast.AST, ...]]:
    """``id(node) -> (outermost_fn, ..., innermost_fn)`` for every node."""
    chains: Dict[int, Tuple[ast.AST, ...]] = {}

    def visit(node: ast.AST, stack: Tuple[ast.AST, ...]) -> None:
        for child in ast.iter_child_nodes(node):
            inner = stack
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                inner = stack + (child,)
            chains[id(child)] = inner
            visit(child, inner)

    visit(tree, ())
    return chains


def build_registry(project: Project) -> Registry:
    """Scan ``raft_tpu/`` for every definition site (see module doc)."""
    reg = Registry()
    for mod in project.walk("raft_tpu/"):
        chains = _enclosing_chains(mod.tree)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            callee = terminal_name(node.func)
            if callee in _METRIC_KINDS:
                kind = callee
            elif callee == "stage":
                kind = "stage"
            elif callee == "maybe_fail":
                kind = "fault_site"
            elif callee in ("span", "SpanRecorder", "start_request"):
                kind = "span"
            elif callee == "record_event":
                kind = "event"
            else:
                continue
            arg = node.args[0]
            name, prefix = _literal_or_prefix(arg)
            if name or prefix:
                reg.add(kind, name, prefix)
                continue
            if not isinstance(arg, ast.Name):
                continue
            # bare-parameter indirection: find the innermost enclosing
            # function declaring this parameter, then harvest the
            # literal arguments of its same-module call sites
            owner, pos = None, None
            for fn in reversed(chains.get(id(node), ())):
                idx = _param_index(fn, arg.id)
                if idx is not None:
                    owner, pos = fn, idx
                    break
            if owner is None:
                continue
            default = _param_default(owner, pos)
            if default is not None:
                name, prefix = _literal_or_prefix(default)
                reg.add(kind, name, prefix)
            for call in _calls_of(mod.tree, owner.name):
                if pos < len(call.args):
                    name, prefix = _literal_or_prefix(call.args[pos])
                    reg.add(kind, name, prefix)
    return reg
