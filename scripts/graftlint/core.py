"""graftlint core: parsed-module model, pass registry, suppression, runner.

A *pass* is a class with a ``name``, the ``rules`` it may emit, a
``doc`` line per rule, and ``run(project) -> [Diagnostic]``.  Passes
register themselves via :func:`register` at import time (see
``passes/__init__.py``); the CLI and the test harness both drive them
through :func:`run_passes`.

Suppressions are per-line comments::

    something_flagged()  # graftlint: disable=rule-name -- why it is ok

``disable`` with no ``=rule`` list suppresses every rule on that line.
A comment-only suppression line also covers the line directly below it
(for expressions too long to share a line with their justification).
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
import re
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Tuple

REPO_ROOT = pathlib.Path(__file__).resolve().parent.parent.parent

# directories the default project scan covers, relative to the root.
# raft_tpu is the analysis subject; tests ride along for the
# registry-consistency reference side (a typo'd counter asserted in a
# test reads 0 forever and the test "passes" vacuously).
DEFAULT_SCAN = ("raft_tpu", "tests")

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable(?:=([A-Za-z0-9_,\-]+))?")


@dataclasses.dataclass(frozen=True, order=True)
class Diagnostic:
    """One finding: ``file:line: rule: message``."""

    path: str          # repo-relative, forward slashes
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"

    def as_dict(self) -> Dict[str, object]:
        return {"file": self.path, "line": self.line, "rule": self.rule,
                "message": self.message}


class Module:
    """One parsed source file plus its suppression table."""

    def __init__(self, rel: str, source: str) -> None:
        self.rel = rel.replace("\\", "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=self.rel)
        self.suppressions: Dict[int, set] = {}
        for i, text in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = (set(r.strip() for r in m.group(1).split(","))
                     if m.group(1) else {"*"})
            self.suppressions.setdefault(i, set()).update(rules)
            # a comment-only suppression line covers the next line too
            if text.strip().startswith("#"):
                self.suppressions.setdefault(i + 1, set()).update(rules)

    def suppressed(self, line: int, rule: str) -> bool:
        rules = self.suppressions.get(line)
        return bool(rules) and (rule in rules or "*" in rules)

    def in_dir(self, *prefixes: str) -> bool:
        return any(self.rel.startswith(p) for p in prefixes)


class Project:
    """The set of modules a lint run sees."""

    def __init__(self, modules: Iterable[Module],
                 root: Optional[pathlib.Path] = None) -> None:
        self.root = root or REPO_ROOT
        self.modules: List[Module] = list(modules)
        self.by_rel: Dict[str, Module] = {m.rel: m for m in self.modules}
        self.errors: List[Diagnostic] = []

    @classmethod
    def from_sources(cls, sources: Dict[str, str]) -> "Project":
        """Build an in-memory project from ``{relpath: source}`` — the
        fixture entry point the graftlint tests use."""
        return cls(Module(rel, src) for rel, src in sources.items())

    def walk(self, *prefixes: str) -> Iterator[Module]:
        for m in self.modules:
            if not prefixes or m.in_dir(*prefixes):
                yield m


def load_project(root: Optional[pathlib.Path] = None,
                 scan: Tuple[str, ...] = DEFAULT_SCAN) -> Project:
    """Parse every ``*.py`` under the scan roots into a Project.

    Unparseable files become synthetic ``parse-error`` diagnostics
    rather than aborting the run — a syntax error in one file must not
    hide findings in the rest of the tree."""
    root = root or REPO_ROOT
    modules, errors = [], []
    for top in scan:
        base = root / top
        if base.is_file():
            paths = [base]
        else:
            paths = sorted(base.rglob("*.py"))
        for path in paths:
            if "__pycache__" in path.parts:
                continue
            rel = path.relative_to(root).as_posix()
            try:
                modules.append(Module(rel, path.read_text()))
            except SyntaxError as e:
                errors.append(Diagnostic(rel, e.lineno or 1, "parse-error",
                                         f"could not parse: {e.msg}"))
    project = Project(modules, root=root)
    project.errors = errors
    return project


# ---------------------------------------------------------------------------
# pass registry

_PASSES: Dict[str, type] = {}


def register(cls: type) -> type:
    """Class decorator: add a pass to the global registry."""
    _PASSES[cls.name] = cls
    return cls


def all_passes() -> Dict[str, type]:
    return dict(_PASSES)


def rule_docs() -> Dict[str, str]:
    """``{rule: one-line invariant}`` across every registered pass."""
    out: Dict[str, str] = {}
    for cls in _PASSES.values():
        out.update(cls.docs)
    return out


def run_passes(project: Project,
               rules: Optional[Iterable[str]] = None,
               ) -> Tuple[List[Diagnostic], int]:
    """Run every registered pass (optionally filtered to ``rules``) over
    the project.  Returns ``(diagnostics, n_suppressed)`` with
    diagnostics sorted by (file, line, rule) and suppressed findings
    dropped (but counted)."""
    wanted = set(rules) if rules is not None else None
    diags: List[Diagnostic] = list(project.errors)
    suppressed = 0
    for cls in _PASSES.values():
        if wanted is not None and not (wanted & set(cls.docs)):
            continue
        for d in cls().run(project):
            if wanted is not None and d.rule not in wanted:
                continue
            mod = project.by_rel.get(d.path)
            if mod is not None and mod.suppressed(d.line, d.rule):
                suppressed += 1
                continue
            diags.append(d)
    return sorted(diags), suppressed


# ---------------------------------------------------------------------------
# shared AST helpers (used by several passes)

def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(node: ast.AST) -> Optional[str]:
    """The last identifier of a call target: ``x.y.f`` -> ``f``."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def contains(node: ast.AST, predicate: Callable[[ast.AST], bool]) -> bool:
    return any(predicate(n) for n in ast.walk(node))


def walk_functions(tree: ast.AST
                   ) -> Iterator[Tuple[ast.AST, Tuple[ast.AST, ...]]]:
    """Yield ``(function_def, enclosing_stack)`` for every (async)
    function at any nesting depth; the stack is outermost-first and
    excludes the function itself."""
    def visit(node: ast.AST, stack: Tuple[ast.AST, ...]) -> Iterator:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child, stack
                yield from visit(child, stack + (child,))
            else:
                yield from visit(child, stack)
    yield from visit(tree, ())


def import_aliases(tree: ast.AST) -> Dict[str, str]:
    """Map local names to the fully-qualified thing they import:
    ``import time as t`` -> ``{"t": "time"}``; ``from time import
    sleep`` -> ``{"sleep": "time.sleep"}``."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out
